"""MPS9xx — compile-surface rules.

MPS901  unbounded shape polymorphism on a serving path: a signature
        dimension at a ``compile_watch.begin`` site classifies as
        *unbounded* with no ``# mpcshape: unbounded-ok`` annotation,
        and the site is reachable from a protocol-phase entry point.
        Every distinct value of that dim is a fresh XLA compile an
        operator pays at serving time — bucket it (engine/buckets.py)
        or annotate the contract that bounds it.
MPS902  retrace-per-call hazards at jit call sites: a loop variable
        flowing into a static parameter (one compile per iteration), or
        ``len(<param>)`` fed to a static parameter (one compile per
        input size) — the class of bug PR 10 hand-fixed in prg_expand
        by making the block offset traced.
MPS903  a jit body closing over a module-level np./jnp. array of
        provably >= 4096 elements: the array is constant-folded into
        every jaxpr that references it, bloating each compiled
        executable (pass it as an argument instead).
MPS904  dtype instability: the same traced jit parameter receives
        explicitly different dtypes across call sites — each distinct
        dtype is a separate compile of the same kernel.
MPS905  vmap-axis misuse: non-constant ``in_axes``/``out_axes`` — a
        fresh axes spec is a fresh jaxpr.
MPS906  use-after-donate: a jit callee with ``donate_argnums`` whose
        caller reads the donated argument after the call site —
        donation invalidates the buffer. Rebinding-aware: the carried
        round-state pattern ``st = round_step(st)`` (engine pipeline,
        ISSUE 17) re-binds the name at the call, so later reads see the
        fresh value and are NOT flagged; only reads with no intervening
        rebind are.

All findings carry mpclint's line-number-free fingerprints and flow
through the shared baseline; ``# mpclint: disable=MPS90x`` suppressions
work as for every other rule family.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from ..core import Finding
from ..flow.callgraph import CallGraph
from ..flow.symbols import FuncInfo, ProjectIndex, _dotted
from .jits import JitInventory
from .sigs import BeginSite

MPS903_MIN_ELEMENTS = 4096

_VMAP_NAMES = ("jax.vmap", "vmap")
_DTYPE_CTORS = {
    "uint8", "uint16", "uint32", "uint64", "int8", "int16", "int32",
    "int64", "float16", "float32", "float64", "bfloat16",
}


def _finding(rule: str, fi: FuncInfo, line: int, key: str,
             message: str) -> Finding:
    return Finding(rule=rule, path=fi.pf.rel, line=line,
                   symbol=fi.qualname, key=key, message=message)


# -- MPS901 ------------------------------------------------------------------


def check_unbounded_serving(sites: Sequence[BeginSite],
                            index: ProjectIndex) -> Iterator[Finding]:
    for site in sites:
        if not site.serving:
            continue
        fi = index.functions[site.fid]
        for d in site.dims:
            if d.cls != "unbounded" or d.annotated:
                continue
            yield _finding(
                "MPS901", fi, site.line, f"{site.engine}:{d.name}",
                f"signature dim {d.name!r} of engine {site.engine!r} is "
                f"unbounded ({d.source}) on a serving path — every value "
                f"is a fresh XLA compile; bucket it (engine/buckets.py) "
                f"or annotate '# mpcshape: unbounded-ok — reason'",
            )


# -- MPS902 ------------------------------------------------------------------


def _static_args_at_call(entry, call: ast.Call):
    """(param, expr) pairs for arguments landing on static parameters."""
    out = []
    params = entry.params
    for i, a in enumerate(call.args):
        if i < len(params) and params[i] in entry.static:
            out.append((params[i], a))
    for kw in call.keywords:
        if kw.arg in entry.static:
            out.append((kw.arg, kw.value))
    return out


def _loop_vars(fi: FuncInfo) -> Dict[int, Set[str]]:
    """For-loop target names by the loop's body span (approx: all names
    bound by any enclosing For in the function)."""
    vars_: Set[str] = set()
    for node in ast.walk(fi.node):
        if isinstance(node, ast.For):
            for n in ast.walk(node.target):
                if isinstance(n, ast.Name):
                    vars_.add(n.id)
    return vars_  # type: ignore[return-value]


def _call_inside_loop(fi: FuncInfo, call: ast.Call) -> bool:
    for node in ast.walk(fi.node):
        if isinstance(node, (ast.For, ast.While)):
            for sub in ast.walk(node):
                if sub is call:
                    return True
    return False


def check_retrace_per_call(index: ProjectIndex, graph: CallGraph,
                           inventory: JitInventory) -> Iterator[Finding]:
    for fid, fi in sorted(index.functions.items()):
        loop_vars = _loop_vars(fi)
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            entry = inventory.resolve_call(graph, fi, node)
            if entry is None or not entry.static:
                continue
            for param, expr in _static_args_at_call(entry, node):
                names = {
                    n.id for n in ast.walk(expr) if isinstance(n, ast.Name)
                }
                hot = sorted(names & loop_vars)
                if hot and _call_inside_loop(fi, node):
                    yield _finding(
                        "MPS902", fi, node.lineno,
                        f"{entry.name}:{param}:loop",
                        f"loop variable {hot[0]!r} reaches static param "
                        f"{param!r} of jit entry {entry.name!r} — one "
                        f"recompile per iteration; make it traced or "
                        f"hoist the variation out of the static arg",
                    )
                    continue
                if (
                    isinstance(expr, ast.Call)
                    and _dotted(expr.func) == "len"
                    and expr.args
                    and isinstance(expr.args[0], ast.Name)
                    and expr.args[0].id in fi.params
                ):
                    yield _finding(
                        "MPS902", fi, node.lineno,
                        f"{entry.name}:{param}:len",
                        f"len({expr.args[0].id}) feeds static param "
                        f"{param!r} of jit entry {entry.name!r} — one "
                        f"recompile per input size; bucket the length "
                        f"(engine/buckets.py) or make the dim traced",
                    )


# -- MPS903 ------------------------------------------------------------------


def _literal_elements(call: ast.Call) -> Optional[int]:
    """Element count of an np./jnp. constructor call when provable."""
    dotted = _dotted(call.func)
    if not (dotted.startswith(("np.", "numpy.", "jnp.", "jax.numpy."))):
        return None
    leaf = dotted.rsplit(".", 1)[-1]

    def count(node) -> Optional[int]:
        if isinstance(node, (ast.List, ast.Tuple)):
            total = 0
            for e in node.elts:
                c = count(e)
                if c is None:
                    return None
                total += c
            return total
        if isinstance(node, ast.Constant):
            return 1
        return None

    if leaf in ("array", "asarray") and call.args:
        return count(call.args[0])
    if leaf == "arange" and call.args:
        ints = [
            a.value for a in call.args
            if isinstance(a, ast.Constant) and isinstance(a.value, int)
        ]
        if len(ints) == len(call.args) and ints:
            if len(ints) == 1:
                return max(0, ints[0])
            step = ints[2] if len(ints) > 2 else 1
            return max(0, (ints[1] - ints[0]) // (step or 1))
        return None
    if leaf in ("zeros", "ones", "full", "empty") and call.args:
        shape = call.args[0]
        if isinstance(shape, ast.Constant) and isinstance(shape.value, int):
            return shape.value
        if isinstance(shape, (ast.Tuple, ast.List)):
            total = 1
            for e in shape.elts:
                if not (isinstance(e, ast.Constant)
                        and isinstance(e.value, int)):
                    return None
                total *= e.value
            return total
    return None


def _module_array_sizes(pf) -> Dict[str, Tuple[int, int]]:
    """module-level name -> (elements, lineno) for provably-large arrays."""
    out: Dict[str, Tuple[int, int]] = {}
    for node in pf.tree.body:
        if not (
            isinstance(node, ast.Assign)
            and len(node.targets) == 1
            and isinstance(node.targets[0], ast.Name)
            and isinstance(node.value, ast.Call)
        ):
            continue
        n = _literal_elements(node.value)
        if n is not None and n >= MPS903_MIN_ELEMENTS:
            out[node.targets[0].id] = (n, node.lineno)
    return out


def check_large_closure_constants(
    index: ProjectIndex, inventory: JitInventory
) -> Iterator[Finding]:
    sizes_by_rel: Dict[str, Dict[str, Tuple[int, int]]] = {}
    for entry in inventory.entries:
        fi = index.functions.get(entry.target_fid or "")
        if fi is None:
            continue
        if fi.pf.rel not in sizes_by_rel:
            sizes_by_rel[fi.pf.rel] = _module_array_sizes(fi.pf)
        sizes = sizes_by_rel[fi.pf.rel]
        if not sizes:
            continue
        bound = set(fi.params)
        for node in ast.walk(fi.node):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    for n in ast.walk(t):
                        if isinstance(n, ast.Name):
                            bound.add(n.id)
        seen: Set[str] = set()
        for node in ast.walk(fi.node):
            if not (isinstance(node, ast.Name)
                    and isinstance(node.ctx, ast.Load)):
                continue
            if node.id in bound or node.id in seen or node.id not in sizes:
                continue
            seen.add(node.id)
            n_el, _ln = sizes[node.id]
            yield _finding(
                "MPS903", fi, node.lineno, f"{entry.name}:{node.id}",
                f"jit body {entry.name!r} closes over module-level array "
                f"{node.id!r} (~{n_el} elements) — constant-folded into "
                f"every jaxpr referencing it; pass it as an argument",
            )


# -- MPS904 ------------------------------------------------------------------


def _dtype_token(expr: ast.AST) -> Optional[str]:
    """An explicit dtype evident at a call-site argument, if any."""
    if isinstance(expr, ast.Constant):
        if isinstance(expr.value, bool):
            return None
        if isinstance(expr.value, int):
            return "weak_int"
        if isinstance(expr.value, float):
            return "weak_float"
        return None
    if not isinstance(expr, ast.Call):
        return None
    dotted = _dotted(expr.func)
    leaf = dotted.rsplit(".", 1)[-1]
    if leaf in _DTYPE_CTORS and dotted.startswith(
        ("np.", "numpy.", "jnp.", "jax.numpy.")
    ):
        return leaf
    if leaf == "astype" and expr.args:
        t = _dotted(expr.args[0]).rsplit(".", 1)[-1]
        return t if t in _DTYPE_CTORS else None
    for kw in expr.keywords:
        if kw.arg == "dtype":
            t = _dotted(kw.value).rsplit(".", 1)[-1]
            if t in _DTYPE_CTORS:
                return t
            if isinstance(kw.value, ast.Constant) and isinstance(
                kw.value.value, str
            ):
                return kw.value.value
    return None


def check_dtype_instability(index: ProjectIndex, graph: CallGraph,
                            inventory: JitInventory) -> Iterator[Finding]:
    per_param: Dict[Tuple[int, str], Dict[str, Tuple[FuncInfo, int]]] = {}
    entries: Dict[int, object] = {}
    for fid, fi in sorted(index.functions.items()):
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            entry = inventory.resolve_call(graph, fi, node)
            if entry is None:
                continue
            entries[id(entry)] = entry
            params = entry.params
            pairs = [
                (params[i], a) for i, a in enumerate(node.args)
                if i < len(params) and params[i] not in entry.static
            ] + [
                (kw.arg, kw.value) for kw in node.keywords
                if kw.arg and kw.arg not in entry.static
            ]
            for pname, expr in pairs:
                tok = _dtype_token(expr)
                if tok is None:
                    continue
                per_param.setdefault(
                    (id(entry), pname), {}
                ).setdefault(tok, (fi, node.lineno))
    for (eid, pname), toks in sorted(
        per_param.items(), key=lambda kv: (entries[kv[0][0]].symbol, kv[0][1])
    ):
        if len(toks) < 2:
            continue
        entry = entries[eid]
        fi, line = sorted(toks.values(), key=lambda v: (v[0].pf.rel, v[1]))[0]
        yield _finding(
            "MPS904", fi, line, f"{entry.name}:{pname}",
            f"traced param {pname!r} of jit entry {entry.name!r} receives "
            f"conflicting explicit dtypes across call sites "
            f"({', '.join(sorted(toks))}) — each dtype is a separate "
            f"compile; pin one dtype at the boundary",
        )


# -- MPS905 ------------------------------------------------------------------


def _axes_static(node: ast.AST) -> bool:
    if isinstance(node, ast.Constant):
        return True
    if isinstance(node, (ast.Tuple, ast.List)):
        return all(_axes_static(e) for e in node.elts)
    if isinstance(node, ast.Dict):
        return all(_axes_static(v) for v in node.values)
    if isinstance(node, ast.UnaryOp) and isinstance(node.op, ast.USub):
        return _axes_static(node.operand)
    return False


def check_vmap_donation(index: ProjectIndex, graph: CallGraph,
                        inventory: JitInventory) -> Iterator[Finding]:
    for fid, fi in sorted(index.functions.items()):
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            if _dotted(node.func) in _VMAP_NAMES:
                target = (
                    _dotted(node.args[0]) if node.args else "?"
                ) or "?"
                for kw in node.keywords:
                    if kw.arg in ("in_axes", "out_axes") and not _axes_static(
                        kw.value
                    ):
                        yield _finding(
                            "MPS905", fi, node.lineno,
                            f"{target}:{kw.arg}",
                            f"non-constant {kw.arg} on vmap of {target!r} "
                            f"— every distinct axes spec traces a fresh "
                            f"jaxpr; use literal axes",
                        )


# -- MPS906 ------------------------------------------------------------------


def check_use_after_donate(index: ProjectIndex, graph: CallGraph,
                           inventory: JitInventory) -> Iterator[Finding]:
    """Use-after-donate, rebinding-aware. The donated-round-state
    engines chain ``st = round_step(st)``: the assignment re-binds the
    name at the call line, so every later read sees the step's OUTPUT
    pytree, not the donated input buffer — those are clean. A read of
    the donated name with NO intervening rebind is a live bug: XLA may
    already have reused the buffer."""
    for fid, fi in sorted(index.functions.items()):
        stores: Dict[str, List[int]] = {}
        for n in ast.walk(fi.node):
            if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Store):
                stores.setdefault(n.id, []).append(n.lineno)
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            entry = inventory.resolve_call(graph, fi, node)
            if entry is None or not entry.donate:
                continue
            params = entry.params
            donated = [
                (params[i], a) for i, a in enumerate(node.args)
                if i < len(params) and params[i] in entry.donate
                and isinstance(a, ast.Name)
            ] + [
                (kw.arg, kw.value) for kw in node.keywords
                if kw.arg in entry.donate and isinstance(kw.value, ast.Name)
            ]
            for pname, name_node in donated:
                for later in ast.walk(fi.node):
                    if (
                        isinstance(later, ast.Name)
                        and isinstance(later.ctx, ast.Load)
                        and later.id == name_node.id
                        and later.lineno > node.lineno
                    ):
                        if any(
                            node.lineno <= r < later.lineno
                            for r in stores.get(later.id, ())
                        ):
                            # re-bound between the donating call and
                            # this read — the name now holds the step's
                            # output, not the donated buffer
                            continue
                        yield _finding(
                            "MPS906", fi, later.lineno,
                            f"{entry.name}:{pname}:donated-reuse",
                            f"{name_node.id!r} is donated to jit entry "
                            f"{entry.name!r} (param {pname!r}) but read "
                            f"afterwards with no rebind — donation "
                            f"invalidates the buffer; rebind the name "
                            f"(st = step(st)), drop the later read, or "
                            f"drop the donation",
                        )
                        break


RULE_IDS = ("MPS901", "MPS902", "MPS903", "MPS904", "MPS905", "MPS906")


def run_rules(index: ProjectIndex, graph: CallGraph,
              inventory: JitInventory,
              sites: Sequence[BeginSite]) -> List[Finding]:
    findings: List[Finding] = []
    findings.extend(check_unbounded_serving(sites, index))
    findings.extend(check_retrace_per_call(index, graph, inventory))
    findings.extend(check_large_closure_constants(index, inventory))
    findings.extend(check_dtype_instability(index, graph, inventory))
    findings.extend(check_vmap_donation(index, graph, inventory))
    findings.extend(check_use_after_donate(index, graph, inventory))
    # central suppression + fingerprint dedupe (mirrors lint_parsed)
    by_rel = {pf.rel: pf for pf in index.files}
    out: List[Finding] = []
    seen: Set[str] = set()
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.rule, f.key)):
        pf = by_rel.get(f.path)
        if pf is not None and pf.is_suppressed(f.rule, f.line):
            continue
        if f.fingerprint in seen:
            continue
        seen.add(f.fingerprint)
        out.append(f)
    return out
