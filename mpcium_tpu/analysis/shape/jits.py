"""The jit entry-point inventory.

Every way this codebase creates a compiled callable is enumerated here,
because each one is a row in COMPILE_SURFACE.json and a potential
retrace hazard for the MPS9xx rules:

- **decorated defs** — ``@jax.jit``, ``@jit``, ``@jax.jit(...)`` and
  ``@functools.partial(jax.jit, static_argnames=...)`` (the dominant
  form in engine/ and ops/);
- **wrapped assignments** — ``name = jax.jit(fn, static_argnums=...)``
  at module or class scope (``ot_transpose_device`` in ops/hash_suite);
- **vmap wrappers** — ``name = jax.vmap(fn, in_axes=...)`` (a vmap of a
  jitted core is still one compile per outer shape).

Static parameters are resolved to *names* (argnums are mapped through
the wrapped function's parameter list) so call-site checks can match
keyword and positional arguments alike. ``donate`` carries
``donate_argnums``-declared parameter names for MPS905.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set

from ..core import ParsedFile
from ..flow.symbols import FuncInfo, ProjectIndex, _dotted

_JIT_NAMES = ("jax.jit", "jit", "jax.pjit", "pjit")
_VMAP_NAMES = ("jax.vmap", "vmap")


class JitEntry:
    """One compiled entry point (a def or a wrapping assignment)."""

    __slots__ = (
        "path", "symbol", "kind", "params", "static", "donate",
        "target_fid", "line", "node",
    )

    def __init__(self, path: str, symbol: str, kind: str,
                 params: Sequence[str], static: Set[str],
                 donate: Set[str], target_fid: Optional[str],
                 line: int, node: ast.AST):
        self.path = path
        self.symbol = symbol  # dotted name callers use
        self.kind = kind  # "jit" | "wrapped" | "vmap"
        self.params = list(params)
        self.static = set(static)
        self.donate = set(donate)
        self.target_fid = target_fid  # underlying def when resolvable
        self.line = line
        self.node = node

    @property
    def name(self) -> str:
        return self.symbol.rsplit(".", 1)[-1]

    def row(self) -> Dict[str, object]:
        return {
            "path": self.path,
            "symbol": self.symbol,
            "kind": self.kind,
            "params": self.params,
            "static": sorted(self.static),
        }


def _const_strs(node: ast.AST) -> List[str]:
    return [
        c.value
        for c in ast.walk(node)
        if isinstance(c, ast.Constant) and isinstance(c.value, str)
    ]


def _const_ints(node: ast.AST) -> List[int]:
    return [
        c.value
        for c in ast.walk(node)
        if isinstance(c, ast.Constant) and isinstance(c.value, int)
        and not isinstance(c.value, bool)
    ]


def _static_from_keywords(
    keywords: Sequence[ast.keyword], params: Sequence[str]
) -> Set[str]:
    static: Set[str] = set()
    for kw in keywords:
        if kw.arg == "static_argnames":
            static.update(_const_strs(kw.value))
        elif kw.arg == "static_argnums":
            for i in _const_ints(kw.value):
                if 0 <= i < len(params):
                    static.add(params[i])
    return static


def _donate_from_keywords(
    keywords: Sequence[ast.keyword], params: Sequence[str]
) -> Set[str]:
    donate: Set[str] = set()
    for kw in keywords:
        if kw.arg == "donate_argnames":
            donate.update(_const_strs(kw.value))
        elif kw.arg == "donate_argnums":
            for i in _const_ints(kw.value):
                if 0 <= i < len(params):
                    donate.add(params[i])
    return donate


def _decorator_jit(fi: FuncInfo) -> Optional[JitEntry]:
    """A JitEntry for a jit-decorated def, else None."""
    for dec in fi.node.decorator_list:
        name = _dotted(dec)
        if name in _JIT_NAMES:
            return JitEntry(fi.pf.rel, fi.qualname, "jit", fi.params,
                            set(), set(), fi.fid, fi.node.lineno, fi.node)
        if isinstance(dec, ast.Call):
            cname = _dotted(dec.func)
            inner = _dotted(dec.args[0]) if dec.args else ""
            if cname in _JIT_NAMES or (
                cname.endswith("partial") and inner in _JIT_NAMES
            ):
                return JitEntry(
                    fi.pf.rel, fi.qualname, "jit", fi.params,
                    _static_from_keywords(dec.keywords, fi.params),
                    _donate_from_keywords(dec.keywords, fi.params),
                    fi.fid, fi.node.lineno, fi.node,
                )
    return None


class JitInventory:
    """Every jit entry in the project, with call-site lookup tables."""

    def __init__(self, index: ProjectIndex):
        self.index = index
        self.entries: List[JitEntry] = []
        self.by_fid: Dict[str, JitEntry] = {}  # decorated-def fid -> entry
        # wrapper-assignment name -> entries (unique-name fallback)
        self.by_name: Dict[str, List[JitEntry]] = {}
        for fi in index.functions.values():
            e = _decorator_jit(fi)
            if e is not None:
                self.entries.append(e)
                self.by_fid[fi.fid] = e
        for pf in index.files:
            self._scan_assignments(pf)
        self.entries.sort(key=lambda e: (e.path, e.symbol))

    def _scan_assignments(self, pf: ParsedFile) -> None:
        for node in ast.walk(pf.tree):
            if not (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, ast.Call)
            ):
                continue
            cname = _dotted(node.value.func)
            if cname in _JIT_NAMES:
                kind = "wrapped"
            elif cname in _VMAP_NAMES:
                kind = "vmap"
            else:
                continue
            assigned = node.targets[0].id
            scope = pf.symbol_of(node)
            symbol = f"{scope}.{assigned}".lstrip(".")
            target_fid = None
            params: List[str] = []
            if node.value.args:
                tgt = self.index.resolve_name_target(
                    pf.rel, _dotted(node.value.args[0])
                )
                if tgt in self.index.functions:
                    target_fid = tgt
                    params = self.index.functions[tgt].params
            entry = JitEntry(
                pf.rel, symbol, kind, params,
                _static_from_keywords(node.value.keywords, params),
                _donate_from_keywords(node.value.keywords, params),
                target_fid, node.lineno, node,
            )
            self.entries.append(entry)
            self.by_name.setdefault(assigned, []).append(entry)

    # -- call-site resolution ------------------------------------------------

    def resolve_call(self, graph, fi: FuncInfo,
                     call: ast.Call) -> Optional[JitEntry]:
        """The JitEntry a call site compiles through, if any: decorated
        defs resolve through the call graph; wrapper assignments by
        (unique) assigned name."""
        fid = graph.resolve_callee(fi, call.func)
        if fid is not None and fid in self.by_fid:
            return self.by_fid[fid]
        name = _dotted(call.func).rsplit(".", 1)[-1]
        cands = self.by_name.get(name, [])
        if len(cands) == 1:
            return cands[0]
        # several modules define the same wrapper name: same-file wins
        same = [e for e in cands if e.path == fi.pf.rel]
        return same[0] if len(same) == 1 else None
