"""COMPILE_SURFACE.json: build, render, and the runtime matcher.

The committed surface is the static answer to "what is the complete set
of compile signatures this codebase can ever request?" — per engine,
the ``compile_watch.begin`` template plus the class of every signature
dimension, and the full jit entry-point inventory. It is line-number
free (like HOST_TRANSFER_BUDGET.json) so unrelated edits don't churn
it, and byte-for-byte drift-gated by scripts/check_all.py and tier-1.

The *matcher* half is what ``perf/compile_watch.finish`` consults to
stamp each runtime ledger entry ``predicted: true|false``: a runtime
shape string is predicted when some engine record's template matches it
and every captured dim value satisfies its static class —

- ``constant``: equals the statically-known value;
- ``knob``: any non-empty value (finite by configuration);
- ``bucketed``: an integer in the pow-2 bucket set;
- ``unbounded``: any value iff the dim carries an ``unbounded-ok``
  annotation (un-annotated unbounded dims never reach a committed
  surface — the MPS901 gate forbids them).

An unpredicted runtime compile is an analysis gap: the tier-1 test over
committed ledger/bench artifacts fails loudly on one.
"""
from __future__ import annotations

import json
import re
from typing import Dict, List, Optional, Sequence

from ...engine.buckets import BUCKETS, is_bucket
from .jits import JitEntry
from .sigs import BeginSite

SURFACE_BASENAME = "COMPILE_SURFACE.json"

_DIM_RE = re.compile(r"\{([^{}]*)\}")


def build_surface(sites: Sequence[BeginSite],
                  jit_entries: Sequence[JitEntry]) -> Dict[str, object]:
    engines: Dict[str, List[dict]] = {}
    for s in sorted(sites, key=lambda s: (s.engine, s.template, s.path)):
        engines.setdefault(s.engine, []).append({
            "site": {"path": s.path, "symbol": s.symbol},
            "template": s.template,
            "serving": s.serving,
            "finite": s.finite,
            "dims": {d.name: d.row() for d in s.dims},
        })
    jits = [e.row() for e in sorted(
        jit_entries, key=lambda e: (e.path, e.symbol)
    )]
    finite = all(
        rec["finite"] for recs in engines.values() for rec in recs
    )
    return {
        "comment": (
            "Static compile surface (mpcshape MPS9xx): per engine, the "
            "compile_watch.begin signature template with every dimension "
            "classified constant/knob/bucketed/unbounded, plus the full "
            "jit entry-point inventory. perf/compile_watch stamps runtime "
            "ledger entries predicted:true|false against this file; the "
            "ROADMAP-item-4 AOT pre-warmer compiles exactly these "
            "signatures. Regenerate with scripts/mpcshape_surface.py."
        ),
        # the concrete pow-2 grid every "bucketed" dim ranges over —
        # embedded so a BUCKETS change (a new top size) is byte-drift in
        # this file and forces a surface + warm-manifest regen
        "bucket_grid": list(BUCKETS),
        "engines": engines,
        "jit_entries": jits,
        "counts": {
            "engines": len(engines),
            "signatures": sum(len(v) for v in engines.values()),
            "jit_entries": len(jits),
            "finite": finite,
        },
    }


def render(surface: Dict[str, object]) -> str:
    return json.dumps(surface, indent=1, ensure_ascii=False) + "\n"


# -- runtime matcher ---------------------------------------------------------


def load_surface(path: str) -> Optional[Dict[str, object]]:
    try:
        with open(path) as f:
            doc = json.load(f)
    except (OSError, ValueError):
        return None
    return doc if isinstance(doc, dict) and "engines" in doc else None


def _template_regex(template: str) -> "re.Pattern[str]":
    out: List[str] = []
    pos = 0
    i = 0
    for m in _DIM_RE.finditer(template):
        out.append(re.escape(template[pos:m.start()]))
        out.append(f"(?P<d{i}>[^|]*)")
        i += 1
        pos = m.end()
    out.append(re.escape(template[pos:]))
    return re.compile("^" + "".join(out) + "$")


def _dim_ok(row: Dict[str, object], value: str) -> bool:
    cls = row.get("class")
    if cls == "constant":
        want = row.get("value")
        return value == str(want) if want is not None else bool(value)
    if cls == "knob":
        return value != ""
    if cls == "bucketed":
        try:
            return is_bucket(int(value))
        except ValueError:
            return False
    if cls == "unbounded":
        return bool(row.get("annotated"))
    return False


def shape_predicted(surface: Dict[str, object], engine: str,
                    shape: str) -> bool:
    """True when (engine, shape) maps to a static signature record."""
    for rec in surface.get("engines", {}).get(engine, ()):  # type: ignore[union-attr]
        template = rec.get("template", "")
        names = _DIM_RE.findall(template)
        m = _template_regex(template).match(shape)
        if m is None:
            continue
        dims = rec.get("dims", {})
        ok = True
        for i, name in enumerate(names):
            row = dims.get(name)
            if row is None or not _dim_ok(row, m.group(f"d{i}")):
                ok = False
                break
        if ok:
            return True
    return False
