"""Compile-signature extraction from ``compile_watch.begin`` sites.

The shape string an engine passes to ``compile_watch.begin(engine,
f"B{B}|q{q}|...")`` IS its compile-signature key: one XLA compile exists
per distinct value of that f-string. mpcshape parses the JoinedStr into
a *template* (``"B{B}|q{q}"``) whose interpolated expressions are the
signature dimensions, then classifies each dimension by provenance:

- **constant** — statically a fixed value;
- **knob** — an operator/config degree of freedom (quorum size,
  key_type, mta impl, thresholds): finite by configuration. Dimension
  *names* on the knob list classify as knobs regardless of provenance —
  the name is the policy (``q`` is always a config-bounded quorum);
- **bucketed** — provenance flows through ``engine/buckets.py``
  (``floor_bucket``/``bucket_b``): value provably in the pow-2 set;
- **unbounded** — request-varying with no bucketing on the path
  (``len(shares)`` and friends). Allowed only with an explicit
  ``# mpcshape: unbounded-ok — reason`` annotation on the begin line or
  the provenance assignment line; un-annotated unbounded dims on a
  serving-reachable site raise MPS901.

Provenance follows local assignments (including tuple unpacking like
``q, B = self.q, self.B``), ``self.X`` attributes into ``__init__``,
env/config reads, and function parameters, depth-limited — anything it
cannot prove stays unbounded, which is the fail-closed direction.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Tuple

from ..core import ParsedFile
from ..flow.symbols import FuncInfo, ProjectIndex, _dotted

# batch/session-sized names: classified by provenance, never by name
BATCH_DIM_NAMES = {"B", "b", "batch", "bsz", "n_wallets", "n_sessions"}

# config/operator degrees of freedom: finite by configuration; the name
# alone classifies (quorums, thresholds, curve and impl selectors)
KNOB_DIM_NAMES = {
    "q", "q_old", "n", "t", "t_new", "tp1", "threshold", "key_type",
    "mta_impl", "mta", "occ", "chunks", "nblk", "scheme",
}

_BUCKET_FNS = ("floor_bucket", "bucket_b")
_ENV_READS = ("os.environ.get", "environ.get", "os.getenv", "getenv")


class Dim:
    __slots__ = ("name", "cls", "source", "value", "annotated", "reason")

    def __init__(self, name: str, cls: str, source: str,
                 value: Optional[object] = None,
                 annotated: bool = False, reason: str = ""):
        self.name = name
        self.cls = cls  # constant | knob | bucketed | unbounded
        self.source = source
        self.value = value
        self.annotated = annotated
        self.reason = reason

    def row(self) -> Dict[str, object]:
        row: Dict[str, object] = {"class": self.cls, "source": self.source}
        if self.cls == "constant" and self.value is not None:
            row["value"] = self.value
        if self.annotated:
            row["annotated"] = True
            row["reason"] = self.reason
        return row


class BeginSite:
    """One ``compile_watch.begin`` call: an engine's signature template."""

    __slots__ = ("engine", "template", "dims", "path", "symbol", "line",
                 "fid", "serving")

    def __init__(self, engine: str, template: str, dims: List[Dim],
                 path: str, symbol: str, line: int, fid: str):
        self.engine = engine
        self.template = template
        self.dims = dims
        self.path = path
        self.symbol = symbol
        self.line = line
        self.fid = fid
        self.serving = False  # set by the runner from the call graph

    @property
    def finite(self) -> bool:
        return all(
            d.cls in ("constant", "knob", "bucketed") or d.annotated
            for d in self.dims
        )


def _expr_text(e: ast.AST) -> str:
    try:
        return ast.unparse(e)
    except Exception:  # noqa: BLE001 — display-only fallback
        return type(e).__name__


class _Provenance:
    """Depth-limited definition-chasing for one begin site."""

    def __init__(self, fi: FuncInfo, index: ProjectIndex):
        self.fi = fi
        self.index = index
        # (pf, line) trail of visited assignments — annotation lookup
        self.trail: List[Tuple[ParsedFile, int]] = []

    def classify(self, e: ast.AST, fi: Optional[FuncInfo] = None,
                 depth: int = 0) -> Tuple[str, str, Optional[object]]:
        """(class, source, value) for one dim expression."""
        fi = fi or self.fi
        if depth > 6:
            return "unbounded", "provenance depth limit", None
        if isinstance(e, ast.Constant):
            return "constant", "literal", e.value
        if isinstance(e, ast.Name):
            return self._classify_name(e.id, fi, depth)
        if isinstance(e, ast.Attribute):
            owner = e.value
            if isinstance(owner, ast.Name) and owner.id in ("self", "cls"):
                return self._classify_self_attr(e.attr, fi, depth)
            return "unbounded", _expr_text(e), None
        if isinstance(e, ast.Call):
            dotted = _dotted(e.func)
            leaf = dotted.rsplit(".", 1)[-1]
            if leaf in _BUCKET_FNS:
                return "bucketed", f"{leaf}() (engine/buckets.py)", None
            if dotted in _ENV_READS:
                return "knob", f"env {_expr_text(e)}", None
            if dotted == "len":
                return "unbounded", f"len({_expr_text(e.args[0]) if e.args else '?'})", None
            if dotted in ("int", "str"):
                if e.args:
                    return self.classify(e.args[0], fi, depth + 1)
            return "unbounded", _expr_text(e), None
        if isinstance(e, ast.BinOp):
            lc, ls, lv = self.classify(e.left, fi, depth + 1)
            rc, rs, rv = self.classify(e.right, fi, depth + 1)
            order = {"unbounded": 3, "bucketed": 2, "knob": 1, "constant": 0}
            cls = max((lc, rc), key=lambda c: order[c])
            return cls, f"{ls} ∘ {rs}", None
        return "unbounded", _expr_text(e), None

    def _classify_name(self, name: str, fi: FuncInfo, depth: int):
        rhs = self._local_def(name, fi)
        if rhs is not None:
            node, value = rhs
            self.trail.append((fi.pf, node.lineno))
            return self.classify(value, fi, depth + 1)
        if name in fi.params:
            cls = "knob" if name in KNOB_DIM_NAMES else "unbounded"
            return cls, f"param {name}", None
        # module-level constant?
        mod_rhs = self._module_def(name, fi.pf)
        if mod_rhs is not None:
            self.trail.append((fi.pf, mod_rhs.lineno))
            return self.classify(mod_rhs.value, fi, depth + 1)
        return "unbounded", f"unresolved name {name}", None

    def _classify_self_attr(self, attr: str, fi: FuncInfo, depth: int):
        # assignment inside the current function body first (self.x = ...)
        rhs = self._self_def(attr, fi)
        if rhs is None and fi.cls:
            init_fid = self.index.lookup_method(fi.cls, "__init__")
            init = self.index.functions.get(init_fid) if init_fid else None
            if init is not None and init is not fi:
                rhs = self._self_def(attr, init)
                if rhs is not None:
                    node, value = rhs
                    self.trail.append((init.pf, node.lineno))
                    return self.classify(value, init, depth + 1)
        if rhs is not None:
            node, value = rhs
            self.trail.append((fi.pf, node.lineno))
            return self.classify(value, fi, depth + 1)
        return "unbounded", f"unresolved attribute self.{attr}", None

    def _local_def(self, name: str, fi: FuncInfo):
        """Last ``name = ...`` in fi's body (tuple unpacking unpacked)."""
        found = None
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue
            value = self._match_target(node.targets[0], node.value,
                                       lambda t: isinstance(t, ast.Name)
                                       and t.id == name)
            if value is not None:
                found = (node, value)
        return found

    def _self_def(self, attr: str, fi: FuncInfo):
        found = None
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Assign) or len(node.targets) != 1:
                continue

            def hit(t, attr=attr):
                return (
                    isinstance(t, ast.Attribute)
                    and isinstance(t.value, ast.Name)
                    and t.value.id == "self"
                    and t.attr == attr
                )

            value = self._match_target(node.targets[0], node.value, hit)
            if value is not None:
                found = (node, value)
        return found

    def _match_target(self, target, value, pred):
        """The RHS sub-expression assigned to the target ``pred`` picks —
        positional through parallel tuple assignment."""
        if pred(target):
            return value
        if isinstance(target, (ast.Tuple, ast.List)) and isinstance(
            value, (ast.Tuple, ast.List)
        ) and len(target.elts) == len(value.elts):
            for t, v in zip(target.elts, value.elts):
                got = self._match_target(t, v, pred)
                if got is not None:
                    return got
        return None

    def _module_def(self, name: str, pf: ParsedFile):
        for node in pf.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
            ):
                return node
        return None


def _dim_name(e: ast.AST, i: int) -> str:
    if isinstance(e, ast.Name):
        return e.id
    if (
        isinstance(e, ast.Attribute)
        and isinstance(e.value, ast.Name)
        and e.value.id in ("self", "cls")
    ):
        return e.attr
    return f"expr{i}"


def _annotation_reason(site_pf: ParsedFile, begin_line: int,
                       trail: Sequence[Tuple[ParsedFile, int]],
                       ) -> Optional[str]:
    """The unbounded-ok reason covering this dim: the begin line (or the
    line above it) or any provenance assignment line."""
    for ln in (begin_line, begin_line - 1):
        if ln in site_pf.shape_ok:
            return site_pf.shape_ok[ln]
    for pf, ln in trail:
        for cand in (ln, ln - 1):
            if cand in pf.shape_ok:
                return pf.shape_ok[cand]
    return None


def collect_begin_sites(index: ProjectIndex) -> List[BeginSite]:
    sites: List[BeginSite] = []
    for fid, fi in sorted(index.functions.items()):
        for node in ast.walk(fi.node):
            if not isinstance(node, ast.Call):
                continue
            if _dotted(node.func) != "compile_watch.begin":
                continue
            if len(node.args) < 2:
                continue
            eng = node.args[0]
            if not (isinstance(eng, ast.Constant)
                    and isinstance(eng.value, str)):
                continue
            site = _parse_site(eng.value, node, fi, index)
            sites.append(site)
    return sites


def _parse_site(engine: str, call: ast.Call, fi: FuncInfo,
                index: ProjectIndex) -> BeginSite:
    shape = call.args[1]
    parts: List[str] = []
    dims: List[Dim] = []
    exprs: List[ast.AST] = []
    if isinstance(shape, ast.Constant) and isinstance(shape.value, str):
        parts.append(shape.value)
    elif isinstance(shape, ast.JoinedStr):
        for v in shape.values:
            if isinstance(v, ast.Constant):
                parts.append(str(v.value))
            elif isinstance(v, ast.FormattedValue):
                exprs.append(v.value)
                parts.append("{" + _dim_name(v.value, len(exprs) - 1) + "}")
    else:
        parts.append("{" + _expr_text(shape) + "}")
        exprs.append(shape)
    for i, e in enumerate(exprs):
        name = _dim_name(e, i)
        prov = _Provenance(fi, index)
        cls, source, value = prov.classify(e)
        if cls == "unbounded" and name in KNOB_DIM_NAMES:
            cls, source = "knob", f"knob-named dim ({source})"
        annotated, reason = False, ""
        if cls == "unbounded":
            r = _annotation_reason(fi.pf, call.lineno, prov.trail)
            if r is not None:
                annotated, reason = True, r
        dims.append(Dim(name, cls, source, value, annotated, reason))
    symbol = f"{fi.qualname}"
    return BeginSite(engine, "".join(parts), dims, fi.pf.rel, symbol,
                     call.lineno, fi.fid)
