"""mpcshape: static compile-surface analysis for mpcium_tpu.

The compile wall (ROADMAP item 4: 802–1,401 s of XLA recompile per
shape) has a measurement half (PR 9's compile ledger) and needs a cure
— shape-bucketed AOT pre-warming — whose precondition is a trustworthy
answer to *"what is the complete set of compile signatures this
codebase can ever request?"*. mpcshape answers it statically, on the
same ParsedFile set / symbol table / call graph mpcflow uses:

- **jits.py** enumerates every jit entry point (decorated defs,
  ``name = jax.jit(fn)`` assignments, vmap wrappers) with their static
  and donated parameters;
- **sigs.py** extracts each engine's compile-signature template from
  its ``compile_watch.begin`` site and classifies every signature
  dimension constant / knob / bucketed / unbounded by provenance;
- **rules.py** enforces MPS901–905 (unbounded-dim-on-serving-path,
  retrace-per-call, large closure constants, dtype instability,
  vmap/donation misuse);
- **surface.py** renders the committed, drift-gated
  ``COMPILE_SURFACE.json`` and provides the runtime matcher
  ``perf/compile_watch`` uses to stamp ledger entries ``predicted``.

Findings reuse mpclint's Finding/fingerprint/baseline machinery, so the
shared .mpclint-baseline.json and fail-closed-both-ways gate apply
unchanged (scope ``MPS``).
"""
from __future__ import annotations

from pathlib import Path
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from ..core import LintResult, ParsedFile, parse_project
from ..flow.callgraph import CallGraph
from ..flow.residency import PHASE_ENTRY_POINTS
from ..flow.symbols import ProjectIndex
from .jits import JitEntry, JitInventory
from .rules import RULE_IDS, run_rules
from .sigs import BeginSite, collect_begin_sites
from .surface import SURFACE_BASENAME, build_surface, render, shape_predicted

__all__ = [
    "BeginSite", "JitEntry", "JitInventory", "RULE_IDS",
    "SURFACE_BASENAME", "build_surface", "render", "run_shape",
    "run_shape_parsed", "shape_predicted",
]


def _default_serving_roots() -> Set[str]:
    return {fid for fids in PHASE_ENTRY_POINTS.values() for fid in fids}


def run_shape_parsed(
    files: Sequence[ParsedFile],
    parse_errors: Sequence[str] = (),
    serving_roots: Optional[Iterable[str]] = None,
) -> Tuple[LintResult, Dict[str, object]]:
    """Run the compile-surface analysis over already-parsed files.
    Returns (LintResult with MPS findings, the surface dict)."""
    index = ProjectIndex(files)
    graph = CallGraph(index)
    inventory = JitInventory(index)
    sites = collect_begin_sites(index)
    roots = set(
        serving_roots if serving_roots is not None
        else _default_serving_roots()
    )
    reachable = graph.reachable_from(roots)
    for s in sites:
        s.serving = s.fid in reachable
    findings = run_rules(index, graph, inventory, sites)
    result = LintResult()
    result.files_scanned = len(files)
    result.parse_errors = list(parse_errors)
    result.findings = findings
    return result, build_surface(sites, inventory.entries)


def run_shape(
    paths: Optional[Sequence[Path]] = None,
    root: Optional[Path] = None,
) -> Tuple[LintResult, Dict[str, object]]:
    """Parse + analyze (standalone entry point; the combined gate goes
    through scripts/check_all.py to share the parse with mpclint)."""
    root = root or Path(__file__).resolve().parents[3]
    paths = list(paths) if paths else [root / "mpcium_tpu"]
    files, errors = parse_project(paths, root=root)
    return run_shape_parsed(files, parse_errors=errors)
