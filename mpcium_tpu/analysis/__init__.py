"""mpclint — project-native static analysis (ISSUE 7).

An AST-based, rule-plugin analyzer that mechanically enforces the
invariants this codebase keeps re-learning the hard way:

- **secret hygiene** (MPL1xx): key shares, seeds, OT pads, nonces and
  AEAD keys must never flow into log lines, exception messages or
  ``repr``; secret byte comparisons go through ``hmac.compare_digest``.
- **determinism** (MPL2xx): no wall-clock/PRNG/entropy calls and no
  dict-order iteration over peer sets inside fault-plan decision paths
  or protocol round functions — replay and WAL bit-identity depend on it.
- **lock discipline** (MPL3xx): fields declared via the ``@locked_by``
  annotation may only be written under their lock; the cross-module
  lock-acquisition graph must stay acyclic.
- **jit/retrace hazards** (MPL4xx): no host syncs (``np.*``,
  ``.item()``, scalar coercions) or traced-value branching inside
  ``jax.jit``-compiled bodies.
- **wire/thread hygiene** (MPL5xx): every wire dataclass round-trips
  through ``to_json``/``from_json`` and carries a version field; every
  ``threading.Thread``/``Timer`` is daemonized or registered with the
  conftest leak-checker.
- **hygiene** (MPL6xx): the ruff-class defects (bare ``except:``,
  mutable default args, unused module-level imports) — enforced natively
  because the container has no ruff.

See STATIC_ANALYSIS.md for the annotation registry, suppression syntax
(``# mpclint: disable=<rule> — reason``) and the fail-closed baseline
workflow.
"""
from __future__ import annotations

from .baseline import Baseline, BaselineError, load_baseline
from .core import Finding, LintContext, LintResult, lint_paths, run_lint

__all__ = [
    "Baseline",
    "BaselineError",
    "Finding",
    "LintContext",
    "LintResult",
    "lint_paths",
    "load_baseline",
    "run_lint",
]
