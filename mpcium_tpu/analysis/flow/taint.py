"""MPF7xx: secret-flow taint policy.

Sources
  - functions whose return is marked ``Secret[...]`` (utils/annotations):
    share-store reads, WAL unseal, DKG subshare output, nonce/PRG
    derivation — the engine picks these up from the signature;
  - a curated fid list for sources whose signatures stay unannotated;
  - names that the shared secret taxonomy (analysis/taxonomy.py) calls
    secret (``sk``, ``share``, ``seed``, ``nonce``, …), including
    ``# mpclint: secret``-declared extras.

Sinks
  - MPF701 — logging calls (``log.info`` / ``logger.*`` / ``logging.*``);
  - MPF702 — exception construction in ``raise`` (tainted data formatted
    into an exception message escapes via handlers that log ``str(e)``);
  - MPF703 — persistence/egress of *unsealed* taint: pickle dumps,
    direct file writes, transport publish/broadcast payloads (the bus
    channel-encrypts below this line, but application payloads are the
    documented protection boundary: shares must be sealed or reduced to
    protocol math before they reach the wire API).

Sanitizers (cut taint to CLEAN)
  - AEAD sealing (``seal``/``_seal``/``encrypt`` methods — kvstore,
    session WAL, transport channel, Paillier);
  - hash commitments and KDFs (``hashlib.*``, ``hmac.*``, the native
    batch SHA kernels, ``challenge_hashes``);
  - an explicit ``# mpcflow: declassified`` on the assignment line
    (handled by the engine via ParsedFile.declassified).

Findings carry the full source→sink call chain in the message; the
fingerprint stays line-free (``rule:path:symbol:sink<-origin``).
"""
from __future__ import annotations

import ast
from typing import List, Optional, Tuple

from ..core import Finding
from ..taxonomy import is_secret_name
from .callgraph import CallGraph
from .engine import FlowEngine, Policy
from .symbols import FuncInfo, ProjectIndex

# files the taint pass skips: the analysis package talks about secrets
# in every other line, and tests exercise leaky patterns on purpose
SKIP_PREFIXES = ("mpcium_tpu/analysis/",)

# drill/chaos "seeds" are public replay handles, not key material —
# secret-name seeding is off for the fault-injection package
_PUBLIC_SEED_PREFIXES = ("mpcium_tpu/faults/",)

# attrs that stay clean even on a secret base object: a KeygenShare is
# tainted, but its roster/threshold/public key are wire-public fields
_PUBLIC_ATTRS = {
    "participants", "public_key", "vss_commitments", "threshold",
    "epoch", "key_type", "is_reshared", "describe", "rules",
    "error_reason", "result_type", "session_id", "wallet_id",
}

# sources whose signatures we keep unannotated (fid suffix match:
# "<rel>::<qualname>")
SOURCE_FIDS = {
    "mpcium_tpu/store/kvstore.py::EncryptedFileKV.get":
        "encrypted share-store read",
    "mpcium_tpu/store/kvstore.py::EncryptedFileKV.unseal":
        "AEAD unseal",
    "mpcium_tpu/store/kvstore.py::EncryptedFileKV._open":
        "AEAD unseal",
}

_LOG_OBJECTS = {"log", "logger", "logging", "_logger", "_log"}
_LOG_FUNCS = {
    "debug", "info", "warning", "warn", "error", "exception",
    "critical", "fatal",
}

_HASH_TAILS = {
    "sha256", "sha512", "sha1", "md5", "blake2b", "blake2s",
    "sha3_256", "sha3_512", "scrypt", "pbkdf2_hmac",
    "batch_sha256", "batch_sha512", "challenge_hashes",
    "hashed_name", "hash_token", "compare_digest",
}
# AEAD / encryption boundaries: tainted plaintext in, safe blob out —
# plus outputs that are public by construction: signatures, ZK proofs,
# hash commitments
_SEAL_QUALNAME_TAILS = {
    "seal", "_seal", "encrypt", "encrypt_private_bytes",
    "sign_raw", "prove", "commit",
}
_SANITIZER_FIDS = {
    # Ed25519 envelope signing: the signature is a public output
    "mpcium_tpu/identity/identity.py::InitiatorKey.sign",
}

_WIRE_TAILS = {"publish", "publish_with_reply", "broadcast", "send_direct"}

_FILE_WRITE_DOTTED = {"os.write"}
_FILE_WRITE_TAILS = {"write_bytes", "write_text"}

_PICKLE_DOTTED = {
    "pickle.dump", "pickle.dumps", "marshal.dump", "marshal.dumps",
    "np.save", "np.savez", "numpy.save", "numpy.savez",
}


class TaintPolicy(Policy):
    def __init__(self, index: ProjectIndex):
        self.index = index

    # -- sources -------------------------------------------------------

    def source_call(self, fid: str) -> Optional[str]:
        label = SOURCE_FIDS.get(fid)
        if label:
            return label
        return None

    def source_name(self, name: str, fi: FuncInfo) -> Optional[str]:
        if fi.pf.rel.startswith(_PUBLIC_SEED_PREFIXES):
            return None
        if is_secret_name(name, fi.pf.extra_secrets):
            return f"secret-named '{name}'"
        return None

    def public_attr(self, name: str) -> bool:
        return name in _PUBLIC_ATTRS

    # -- sanitizers ----------------------------------------------------

    def sanitizer(self, fid: Optional[str], dotted: str) -> bool:
        tail = dotted.rsplit(".", 1)[-1] if dotted else ""
        if dotted.startswith(("hashlib.", "hmac.")):
            return True
        if tail in _HASH_TAILS:
            return True
        if fid is not None:
            if fid in _SANITIZER_FIDS:
                return True
            # split off the path first: a module-level fn fid ends
            # "<file>.py::name" and a plain rsplit('.') would yield
            # "py::name" instead of "name"
            qn = fid.split("::", 1)[-1]
            if qn.rsplit(".", 1)[-1] in _SEAL_QUALNAME_TAILS:
                return True
        # unresolved method call spelled like a sealer ('.seal(', '.encrypt(')
        if fid is None and tail in _SEAL_QUALNAME_TAILS:
            return True
        return False

    # -- sinks ---------------------------------------------------------

    def sink(
        self, call: ast.Call, dotted: str, fi: FuncInfo, fid: Optional[str]
    ) -> Optional[Tuple[str, str, str]]:
        tail = dotted.rsplit(".", 1)[-1] if dotted else ""
        base = dotted.rsplit(".", 1)[0] if "." in dotted else ""
        if tail in _LOG_FUNCS and (
            base in _LOG_OBJECTS or base.split(".")[-1] in _LOG_OBJECTS
        ):
            return ("MPF701", "log", dotted)
        if dotted in _PICKLE_DOTTED:
            return ("MPF703", "persist", dotted)
        if dotted in _FILE_WRITE_DOTTED or tail in _FILE_WRITE_TAILS:
            return ("MPF703", "persist", dotted or tail)
        if tail in _WIRE_TAILS and isinstance(call.func, ast.Attribute):
            return ("MPF703", "wire", dotted or tail)
        return None

    def raise_is_sink(self) -> Optional[Tuple[str, str]]:
        return ("MPF702", "raise")


def run_taint(index: ProjectIndex, graph: CallGraph) -> List[Finding]:
    """MPF7xx sweep over an already-built index/graph."""
    policy = TaintPolicy(index)
    engine = FlowEngine(index, graph, policy)
    findings = engine.run()
    return [
        f for f in findings if not f.path.startswith(SKIP_PREFIXES)
    ]
