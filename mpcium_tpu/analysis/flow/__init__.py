"""mpcflow: interprocedural dataflow analysis for mpcium_tpu.

Two analyses share one symbol table + call graph over the same
ParsedFile set mpclint uses (analysis/core.parse_project — parse once,
analyze twice):

- **MPF7xx** secret-flow taint (flow/taint.py): share-store reads, DKG
  outputs and nonce/PRG derivation must never reach logging, exception
  formatting, pickle/file writes, or unsealed wire payloads without
  passing an AEAD seal / hash commitment / explicit declassification.
  Findings carry the full source→sink call chain.
- **MPF8xx** device-residency (flow/residency.py): functions reachable
  from protocol-phase entry points are device-hot; host
  materializations of device arrays on those paths are budgeted sites
  (HOST_TRANSFER_BUDGET.json) that must shrink, not grow.

Findings reuse mpclint's Finding/fingerprint/baseline machinery, so the
shared .mpclint-baseline.json and the fail-closed-both-ways gate apply
unchanged.
"""
from __future__ import annotations

from pathlib import Path
from typing import List, Optional, Sequence, Tuple

from ..core import Finding, LintResult, ParsedFile, parse_project
from .callgraph import CallGraph
from .residency import Site, build_budget, run_residency
from .symbols import ProjectIndex
from .taint import run_taint

__all__ = [
    "CallGraph", "ProjectIndex", "Site", "build_budget",
    "run_flow", "run_flow_parsed",
]


def run_flow_parsed(
    files: Sequence[ParsedFile],
    parse_errors: Sequence[str] = (),
) -> Tuple[LintResult, List[Site]]:
    """Run both analyses over already-parsed files. Returns the combined
    LintResult (taint + residency findings) and the residency site list
    (for the budget)."""
    index = ProjectIndex(files)
    graph = CallGraph(index)
    findings: List[Finding] = list(run_taint(index, graph))
    res_findings, sites = run_residency(index, graph)
    findings.extend(res_findings)
    result = LintResult()
    result.files_scanned = len(files)
    result.parse_errors = list(parse_errors)
    result.findings = sorted(
        findings, key=lambda f: (f.path, f.line, f.rule, f.key)
    )
    return result, sites


def run_flow(
    paths: Optional[Sequence[Path]] = None,
    root: Optional[Path] = None,
) -> Tuple[LintResult, List[Site]]:
    """Parse + analyze (standalone entry point; the combined gate goes
    through scripts/check_all.py to share the parse with mpclint)."""
    root = root or Path(__file__).resolve().parents[3]
    paths = list(paths) if paths else [root / "mpcium_tpu"]
    files, errors = parse_project(paths, root=root)
    return run_flow_parsed(files, parse_errors=errors)
