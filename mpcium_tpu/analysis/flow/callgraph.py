"""Call graph over the project symbol table.

Resolves, per function body, every call site to a project fid when it
can:

- plain calls — ``carry(x)`` / ``bn.carry(x)`` via the module import map;
- method calls — ``self._step(x)`` through the enclosing class (and its
  project bases), ``Cls.method(obj, x)`` via the class table;
- constructor calls — ``OTMtALeg(...)`` → ``OTMtALeg.__init__``;
- closures — a nested ``def`` invoked by name in its enclosing scope;
- first-class passing — **local aliasing** (``fn = self._hash_rows``
  then ``fn(x)``) and **unique-method fallback**: ``obj.run_multi(...)``
  on an unknown receiver resolves iff exactly one project class defines
  ``run_multi`` (true for the protocol/engine names we care about; a
  name defined by many classes stays unresolved rather than guessing).

Edges carry the call line so taint findings can print real chains.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set

from .symbols import FuncInfo, FuncNode, ProjectIndex, _dotted

# names too generic for the unique-method fallback even when unique
_FALLBACK_BLOCKLIST = {
    "get", "put", "close", "run", "start", "stop", "append", "send",
    "recv", "read", "write", "update", "items", "keys", "values",
}


class CallSite:
    __slots__ = ("callee", "line", "node")

    def __init__(self, callee: str, line: int, node: ast.Call):
        self.callee = callee  # fid
        self.line = line
        self.node = node


class CallGraph:
    def __init__(self, index: ProjectIndex):
        self.index = index
        self.calls: Dict[str, List[CallSite]] = {}  # caller fid -> sites
        self.callers: Dict[str, Set[str]] = {}  # callee fid -> caller fids
        for fid, fi in index.functions.items():
            sites = list(self._resolve_body(fi))
            self.calls[fid] = sites
            for s in sites:
                self.callers.setdefault(s.callee, set()).add(fid)

    # ------------------------------------------------------------------

    def _resolve_body(self, fi: FuncInfo):
        idx = self.index
        rel = fi.pf.rel
        # one pass for local function-valued aliases:
        #   fn = self._hash_rows   /   step = _kernel
        aliases: Dict[str, str] = {}
        for node in ast.walk(fi.node):
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and isinstance(node.value, (ast.Name, ast.Attribute))
            ):
                tgt = self.resolve_callee(fi, node.value)
                if tgt:
                    aliases[node.targets[0].id] = tgt
        for node in ast.walk(fi.node):
            if isinstance(node, FuncNode) and node is not fi.node:
                # nested def bodies get their own FuncInfo; skip their calls
                continue
            if not isinstance(node, ast.Call):
                continue
            if self._owned_by_nested(fi, node):
                continue
            callee = self.resolve_callee(fi, node.func)
            if callee is None and isinstance(node.func, ast.Name):
                callee = aliases.get(node.func.id)
            if callee is not None and callee in idx.functions:
                yield CallSite(callee, node.lineno, node)
            elif callee is not None and callee in idx.classes:
                init = idx.lookup_method(callee, "__init__")
                if init:
                    yield CallSite(init, node.lineno, node)

    def _owned_by_nested(self, fi: FuncInfo, call: ast.Call) -> bool:
        """True when ``call`` lexically sits inside a nested def — its
        edges belong to the nested function's own fid."""
        for node in ast.walk(fi.node):
            if isinstance(node, FuncNode) and node is not fi.node:
                for sub in ast.walk(node):
                    if sub is call:
                        return True
        return False

    # ------------------------------------------------------------------

    def resolve_callee(self, fi: FuncInfo, func) -> Optional[str]:
        """fid/cid for a call-target expression inside ``fi``, or None."""
        idx = self.index
        rel = fi.pf.rel
        # self.method(...) — enclosing class dispatch (project bases incl.)
        if (
            isinstance(func, ast.Attribute)
            and isinstance(func.value, ast.Name)
            and func.value.id in ("self", "cls")
            and fi.cls
        ):
            m = idx.lookup_method(fi.cls, func.attr)
            if m:
                return m
        dotted = _dotted(func)
        if dotted:
            tgt = idx.resolve_name_target(rel, dotted)
            if tgt:
                return tgt
            # closure: nested def in an enclosing function of this one
            if "." not in dotted:
                scope: Optional[str] = fi.fid
                while scope:
                    cand = f"{scope.rsplit('::', 1)[0]}::" + (
                        f"{scope.rsplit('::', 1)[1]}.{dotted}"
                    )
                    if cand in idx.functions:
                        return cand
                    scope = idx.functions[scope].parent_fid if (
                        scope in idx.functions
                    ) else None
        # unique-method fallback for obj.m(...) with unknown receiver
        if isinstance(func, ast.Attribute):
            name = func.attr
            homes = idx.method_homes.get(name, [])
            if len(homes) == 1 and name not in _FALLBACK_BLOCKLIST:
                return idx.lookup_method(homes[0], name)
        return None

    # ------------------------------------------------------------------

    def reachable_from(self, roots: Set[str]) -> Set[str]:
        """Transitive closure of call edges from ``roots`` (fids)."""
        seen: Set[str] = set()
        stack = [r for r in roots if r in self.index.functions]
        while stack:
            fid = stack.pop()
            if fid in seen:
                continue
            seen.add(fid)
            for s in self.calls.get(fid, ()):
                if s.callee not in seen:
                    stack.append(s.callee)
        return seen
