"""MPF8xx: device-residency analysis + the host-transfer budget.

A function is **device-hot** when it is reachable (over the project call
graph) from a protocol-phase entry point — the orchestration methods
that drive jitted kernels (``OTMtALeg.run_multi``,
``BatchedCoSigners.sign``, ``BatchedECDSASigningParty.receive``, …).
Inside device-hot functions, every *host materialization* of a
device-tracked value is a site:

  - ``jax.device_get(x)`` and ``x.block_until_ready()`` — always;
  - ``x.item()`` — always (a device scalar pulled to Python);
  - ``np.asarray(x)`` / ``np.array(x)`` / ``x.tolist()`` /
    ``bool(x)`` / ``int(x)`` / ``float(x)`` — when ``x`` is
    device-tracked (bound from a ``jnp.*`` call, a jitted project
    function, a ``jnp.ndarray``-annotated param/return, or the ``*_d``
    naming convention).

A site annotated ``# mpcflow: host-ok — reason`` is *intentional*: it
raises no finding but is counted in the budget with its reason, so wire
boundaries stay visible without blocking CI. Unannotated sites raise
MPF801 (fix, annotate, or baseline with a justification naming the
ROADMAP item that deletes it).

``build_budget`` emits the per-phase machine-readable budget that
``scripts/mpcflow_budget.py`` writes to ``HOST_TRANSFER_BUDGET.json``
and the tier-1 gate diffs against the committed copy: ROADMAP item 2's
"host touches only wire bytes" is this file monotonically shrinking.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import Finding
from .callgraph import CallGraph
from .symbols import FuncInfo, FuncNode, ProjectIndex, _dotted

RULE = "MPF801"

# phase -> orchestration entry fids (order matters: a function reachable
# from several phases is budgeted under the first one that claims it)
PHASE_ENTRY_POINTS: Dict[str, Tuple[str, ...]] = {
    "ecdsa.mta_ot": (
        "mpcium_tpu/protocol/ecdsa/mta_ot.py::OTMtALeg.__init__",
        "mpcium_tpu/protocol/ecdsa/mta_ot.py::OTMtALeg.run_multi",
        "mpcium_tpu/protocol/ecdsa/mta_ot.py::OTMtALeg.run",
        "mpcium_tpu/protocol/ecdsa/mta_ot.py::OTMtALeg.alice_round1",
        "mpcium_tpu/protocol/ecdsa/mta_ot.py::OTMtALeg.bob_round2_multi",
        "mpcium_tpu/protocol/ecdsa/mta_ot.py::OTMtALeg.alice_round3_multi",
    ),
    "ecdsa.sign": (
        "mpcium_tpu/engine/gg18_batch.py::GG18BatchCoSigners.sign",
        # parties are constructed once per batch: __init__ is hot too
        "mpcium_tpu/protocol/ecdsa/batch_signing.py::"
        "BatchedECDSASigningParty.__init__",
        "mpcium_tpu/protocol/ecdsa/batch_signing.py::"
        "BatchedECDSASigningParty.start",
        "mpcium_tpu/protocol/ecdsa/batch_signing.py::"
        "BatchedECDSASigningParty.receive",
    ),
    "eddsa.sign": (
        "mpcium_tpu/engine/eddsa_batch.py::BatchedCoSigners.sign",
        "mpcium_tpu/engine/sharded.py::sharded_sign",
    ),
    "dkg": (
        "mpcium_tpu/engine/dkg_batch.py::BatchedDKG.run",
        "mpcium_tpu/engine/dkg_batch.py::BatchedReshare.run",
        "mpcium_tpu/protocol/batch_dkg.py::BatchedDKGParty.__init__",
        "mpcium_tpu/protocol/batch_dkg.py::BatchedDKGParty.start",
        "mpcium_tpu/protocol/batch_dkg.py::BatchedDKGParty.receive",
        "mpcium_tpu/protocol/batch_dkg.py::BatchedReshareParty.__init__",
        "mpcium_tpu/protocol/batch_dkg.py::BatchedReshareParty.start",
        "mpcium_tpu/protocol/batch_dkg.py::BatchedReshareParty.receive",
    ),
    "keygen.dealer": (
        "mpcium_tpu/engine/eddsa_batch.py::dealer_keygen_batch",
        "mpcium_tpu/engine/gg18_batch.py::dealer_keygen_secp_batch",
    ),
}

# only code in these trees can be device-hot; serialization helpers in
# wire.py / node/ that a phase reaches operate on host values by design
_HOT_SCOPES = (
    "mpcium_tpu/engine/",
    "mpcium_tpu/ops/",
    "mpcium_tpu/protocol/",
)

_DEVICE_CALL_PREFIXES = ("jnp.", "jax.lax.", "jax.nn.", "lax.")
_MATERIALIZERS = {"np.asarray", "np.array", "numpy.asarray", "numpy.array",
                  "onp.asarray", "onp.array"}
_DEVICE_GET = {"jax.device_get", "device_get"}
_SCALARIZERS = {"bool", "int", "float"}


class Site:
    __slots__ = ("phase", "path", "symbol", "line", "kind", "detail",
                 "intentional", "reason")

    def __init__(self, phase, path, symbol, line, kind, detail,
                 intentional, reason):
        self.phase = phase
        self.path = path
        self.symbol = symbol
        self.line = line
        self.kind = kind
        self.detail = detail
        self.intentional = intentional
        self.reason = reason

    def budget_row(self) -> Dict[str, object]:
        row: Dict[str, object] = {
            "path": self.path,
            "symbol": self.symbol,
            "kind": self.kind,
            "detail": self.detail,
            "intentional": self.intentional,
        }
        if self.reason:
            row["reason"] = self.reason
        return row


def _annotation_is_device(ann) -> bool:
    """True when the annotation mentions a device array type anywhere —
    covers plain ``jnp.ndarray``, ``Tuple[jnp.ndarray, ...]``, and the
    string form."""
    if ann is None:
        return False
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        return "jnp.ndarray" in ann.value or "jax.Array" in ann.value
    for node in ast.walk(ann):
        d = _dotted(node)
        if d in ("jnp.ndarray", "jax.Array"):
            return True
    return False


def device_fn_names(index: ProjectIndex) -> Set[str]:
    """Project function names that *consistently* return device values
    (jitted or ``jnp.ndarray``-annotated everywhere the name is defined).

    Covers calls the graph can't resolve because the callee module is a
    runtime value — ``mod, _ = _curve(key_type); mod.decompress(...)``:
    ``decompress`` is device-returning in both curve modules, so the
    unresolved call is still tracked. Names defined with conflicting
    device-ness anywhere in the project are excluded."""
    seen: Dict[str, Optional[bool]] = {}
    for fi in index.functions.values():
        name = fi.qualname.rsplit(".", 1)[-1]
        is_dev = fi.is_jit or _annotation_is_device(fi.node.returns)
        if name in seen and seen[name] != is_dev:
            seen[name] = None
        else:
            seen[name] = is_dev
    return {n for n, v in seen.items() if v}


class _DeviceTracker:
    """Order-insensitive local device-value inference for one function."""

    def __init__(self, fi: FuncInfo, index: ProjectIndex, graph: CallGraph,
                 dev_names: Optional[Set[str]] = None):
        self.fi = fi
        self.index = index
        self.graph = graph
        self.dev_names = dev_names if dev_names is not None else set()
        self.names: Set[str] = set()
        a = fi.node.args
        for p in a.posonlyargs + a.args + a.kwonlyargs:
            if _annotation_is_device(p.annotation) or p.arg.endswith("_d"):
                self.names.add(p.arg)
        # fixpoint over assignments (bodies are small; 2-3 passes settle)
        assigns = [
            n for n in ast.walk(fi.node)
            if isinstance(n, (ast.Assign, ast.AnnAssign, ast.AugAssign))
        ]
        for _ in range(4):
            changed = False
            for st in assigns:
                value = getattr(st, "value", None)
                if value is None or not self.is_device(value):
                    continue
                targets = (
                    st.targets if isinstance(st, ast.Assign) else [st.target]
                )
                for t in targets:
                    for leaf in self._target_names(t):
                        if leaf not in self.names:
                            self.names.add(leaf)
                            changed = True
            if not changed:
                break

    def _target_names(self, t):
        if isinstance(t, ast.Name):
            yield t.id
        elif isinstance(t, (ast.Tuple, ast.List)):
            for e in t.elts:
                yield from self._target_names(e)
        elif isinstance(t, ast.Starred):
            yield from self._target_names(t.value)
        elif isinstance(t, ast.Attribute):
            d = _dotted(t)
            if d:
                yield d

    def is_device(self, e) -> bool:
        if isinstance(e, ast.Name):
            return e.id in self.names or e.id.endswith("_d")
        if isinstance(e, ast.Attribute):
            d = _dotted(e)
            if d and (d in self.names or d.endswith("_d")):
                return True
            return self.is_device(e.value)
        if isinstance(e, ast.Subscript):
            return self.is_device(e.value)
        if isinstance(e, ast.Call):
            dotted = _dotted(e.func)
            if dotted.startswith(_DEVICE_CALL_PREFIXES):
                return True
            if dotted in _DEVICE_GET or dotted in _MATERIALIZERS:
                return False  # result is a host value
            fid = self.graph.resolve_callee(self.fi, e.func)
            if fid is not None:
                callee = self.index.functions.get(fid)
                if callee is not None and (
                    callee.is_jit
                    or _annotation_is_device(callee.node.returns)
                ):
                    return True
            elif (
                isinstance(e.func, ast.Attribute)
                and e.func.attr in self.dev_names
            ):
                return True
            # method call on a device value keeps device-ness (.reshape…)
            if isinstance(e.func, ast.Attribute) and e.func.attr not in (
                "item", "tolist", "block_until_ready"
            ):
                return self.is_device(e.func.value)
            return False
        if isinstance(e, (ast.Tuple, ast.List)):
            return any(self.is_device(x) for x in e.elts)
        if isinstance(e, ast.BinOp):
            return self.is_device(e.left) or self.is_device(e.right)
        if isinstance(e, ast.IfExp):
            return self.is_device(e.body) or self.is_device(e.orelse)
        if isinstance(e, (ast.Await, ast.Starred)):
            return self.is_device(e.value)
        return False


def classify_hot(index: ProjectIndex, graph: CallGraph) -> Dict[str, str]:
    """fid -> phase for every device-hot function (first phase wins)."""
    hot: Dict[str, str] = {}
    for phase, entries in PHASE_ENTRY_POINTS.items():
        roots = {e for e in entries if e in index.functions}
        for fid in graph.reachable_from(roots):
            fi = index.functions[fid]
            if not fi.pf.rel.startswith(_HOT_SCOPES):
                continue
            hot.setdefault(fid, phase)
    return hot


def _arg_detail(e) -> str:
    d = _dotted(e)
    if d:
        return d
    if isinstance(e, ast.Call):
        return _dotted(e.func) or type(e).__name__
    if isinstance(e, ast.Subscript):
        return _arg_detail(e.value) + "[]"
    return type(e).__name__


def scan_function(
    fi: FuncInfo, phase: str, index: ProjectIndex, graph: CallGraph,
    dev_names: Optional[Set[str]] = None,
) -> List[Site]:
    tracker = _DeviceTracker(fi, index, graph, dev_names)
    nested: Set[int] = set()
    for n in ast.walk(fi.node):
        if isinstance(n, FuncNode) and n is not fi.node:
            for sub in ast.walk(n):
                nested.add(id(sub))
    sites: List[Site] = []

    def add(node, kind: str, detail: str) -> None:
        line = node.lineno
        reason = fi.pf.host_ok.get(line)
        if reason is None:
            reason = fi.pf.host_ok.get(line - 1)  # comment-above style
        intentional = reason is not None
        sites.append(
            Site(phase, fi.pf.rel, fi.qualname, line, kind, detail,
                 intentional, reason or "")
        )

    for node in ast.walk(fi.node):
        if id(node) in nested or not isinstance(node, ast.Call):
            continue
        dotted = _dotted(node.func)
        if dotted in _DEVICE_GET:
            add(node, "device_get",
                _arg_detail(node.args[0]) if node.args else "?")
        elif isinstance(node.func, ast.Attribute):
            attr = node.func.attr
            if attr == "block_until_ready":
                add(node, "block_until_ready", _arg_detail(node.func.value))
            elif attr == "item" and not node.args:
                add(node, "item", _arg_detail(node.func.value))
            elif attr == "tolist" and tracker.is_device(node.func.value):
                add(node, "tolist", _arg_detail(node.func.value))
        if dotted in _MATERIALIZERS and node.args and tracker.is_device(
            node.args[0]
        ):
            add(node, "np.asarray", _arg_detail(node.args[0]))
        elif (
            dotted in _SCALARIZERS
            and node.args
            and tracker.is_device(node.args[0])
        ):
            add(node, f"{dotted}()", _arg_detail(node.args[0]))
    return sites


def run_residency(
    index: ProjectIndex, graph: CallGraph
) -> Tuple[List[Finding], List[Site]]:
    hot = classify_hot(index, graph)
    dev_names = device_fn_names(index)
    all_sites: List[Site] = []
    findings: List[Finding] = []
    for fid, phase in sorted(hot.items()):
        fi = index.functions[fid]
        for site in scan_function(fi, phase, index, graph, dev_names):
            all_sites.append(site)
            if site.intentional:
                continue
            if fi.pf.is_suppressed(RULE, site.line):
                continue
            f = Finding(
                rule=RULE,
                path=site.path,
                line=site.line,
                symbol=site.symbol,
                key=f"{site.kind}:{site.detail}",
                message=(
                    f"host materialization ({site.kind} of {site.detail}) "
                    f"on device-hot path [phase {phase}] — fix, annotate "
                    f"'# mpcflow: host-ok — reason', or baseline against "
                    f"a ROADMAP item"
                ),
            )
            findings.append(f)
    # dedupe by fingerprint (same kind+detail can appear twice in a body)
    uniq: Dict[str, Finding] = {}
    for f in findings:
        uniq.setdefault(f.fingerprint, f)
    return (
        sorted(uniq.values(), key=lambda f: (f.path, f.line, f.rule, f.key)),
        all_sites,
    )


def build_budget(sites: Sequence[Site]) -> Dict[str, object]:
    """The machine-readable host-transfer budget (line-number free so the
    committed JSON survives unrelated edits)."""
    phases: Dict[str, Dict[str, object]] = {}
    seen: Set[Tuple[str, str, str, str, str]] = set()
    for s in sorted(
        sites, key=lambda s: (s.phase, s.path, s.symbol, s.kind, s.detail)
    ):
        k = (s.phase, s.path, s.symbol, s.kind, s.detail)
        if k in seen:
            continue
        seen.add(k)
        ph = phases.setdefault(
            s.phase,
            {"total_sites": 0, "intentional": 0, "tracked": 0, "sites": []},
        )
        ph["total_sites"] += 1  # type: ignore[operator]
        if s.intentional:
            ph["intentional"] += 1  # type: ignore[operator]
        else:
            ph["tracked"] += 1  # type: ignore[operator]
        ph["sites"].append(s.budget_row())  # type: ignore[union-attr]
    return {
        "comment": (
            "Host-transfer budget per protocol phase (mpcflow MPF801). "
            "'intentional' sites carry a '# mpcflow: host-ok' reason "
            "(wire boundaries); 'tracked' sites are baselined debt tied "
            "to ROADMAP items and must monotonically shrink. Regenerate "
            "with scripts/mpcflow_budget.py."
        ),
        "phases": phases,
    }
