"""Project-wide symbol table for mpcflow.

Turns the flat ParsedFile list into what interprocedural analysis needs:

- every function/method/nested-def gets a stable **fid**
  (``rel::dotted.qualname``, e.g.
  ``mpcium_tpu/protocol/ecdsa/mta_ot.py::OTMtALeg.run_multi``);
- per-module import resolution (absolute and relative, alias-aware), so
  ``from ...core import bignum as bn`` lets a call ``bn.carry(x)``
  resolve to ``mpcium_tpu/core/bignum.py::carry``;
- per-class method tables including **project base classes** and
  class-body first-class assignments
  (``_parse_bytes = BatchBlockMixin._parse_block``), so mixin dispatch
  resolves.

Resolution is best-effort and project-scoped: anything outside
``mpcium_tpu`` (stdlib, jax, numpy) resolves to ``None`` and the
engine treats the call conservatively.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Sequence, Set, Tuple

from ..core import ParsedFile

FuncNode = (ast.FunctionDef, ast.AsyncFunctionDef)

PKG = "mpcium_tpu"


def module_of(rel: str) -> str:
    """'mpcium_tpu/core/bignum.py' → 'mpcium_tpu.core.bignum'."""
    mod = rel[:-3] if rel.endswith(".py") else rel
    mod = mod.replace("/", ".")
    if mod.endswith(".__init__"):
        mod = mod[: -len(".__init__")]
    return mod


class FuncInfo:
    """One function/method definition."""

    __slots__ = (
        "fid", "pf", "node", "qualname", "cls", "params", "is_jit",
        "secret_params", "secret_return", "parent_fid",
    )

    def __init__(
        self,
        pf: ParsedFile,
        node,
        qualname: str,
        cls: Optional[str],
        parent_fid: Optional[str],
    ):
        self.pf = pf
        self.node = node
        self.qualname = qualname
        self.fid = f"{pf.rel}::{qualname}"
        self.cls = cls  # "rel::ClassQualname" when a method
        self.parent_fid = parent_fid  # enclosing function (closures)
        a = node.args
        self.params: List[str] = [
            p.arg for p in a.posonlyargs + a.args + a.kwonlyargs
        ]
        self.is_jit = _is_jit_decorated(node)
        # Secret[...] markers (utils/annotations.py)
        self.secret_params: Set[str] = {
            p.arg
            for p in a.posonlyargs + a.args + a.kwonlyargs
            if _is_secret_annotation(p.annotation)
        }
        self.secret_return = _is_secret_annotation(node.returns)

    @property
    def display(self) -> str:
        return f"{self.pf.rel}::{self.qualname}"


def _is_secret_annotation(ann) -> bool:
    """True for ``Secret[...]`` / ``annotations.Secret[...]``, in direct
    or string ('Secret[bytes]') form."""
    if ann is None:
        return False
    if isinstance(ann, ast.Constant) and isinstance(ann.value, str):
        try:
            ann = ast.parse(ann.value, mode="eval").body
        except SyntaxError:
            return False
    if isinstance(ann, ast.Subscript):
        base = ann.value
        name = (
            base.id
            if isinstance(base, ast.Name)
            else base.attr
            if isinstance(base, ast.Attribute)
            else ""
        )
        return name == "Secret"
    return False


def _is_jit_decorated(fn) -> bool:
    for dec in fn.decorator_list:
        name = _dotted(dec)
        if name in ("jax.jit", "jit"):
            return True
        if isinstance(dec, ast.Call):
            cname = _dotted(dec.func)
            if cname in ("jax.jit", "jit"):
                return True
            inner = _dotted(dec.args[0]) if dec.args else ""
            if cname.endswith("partial") and inner in ("jax.jit", "jit"):
                return True
    return False


def _dotted(node) -> str:
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


class ClassInfo:
    __slots__ = ("cid", "pf", "node", "qualname", "methods", "bases")

    def __init__(self, pf: ParsedFile, node: ast.ClassDef, qualname: str):
        self.pf = pf
        self.node = node
        self.qualname = qualname
        self.cid = f"{pf.rel}::{qualname}"
        self.methods: Dict[str, str] = {}  # name -> fid
        self.bases: List[str] = []  # resolved project cids


class ProjectIndex:
    """Symbol table over one ParsedFile set."""

    def __init__(self, files: Sequence[ParsedFile]):
        self.files = list(files)
        self.functions: Dict[str, FuncInfo] = {}  # fid -> info
        self.classes: Dict[str, ClassInfo] = {}  # cid -> info
        # module ('mpcium_tpu.core.bignum') -> rel path
        self.modules: Dict[str, str] = {}
        # (rel, local alias) -> ('module', modname) | ('symbol', fid/cid)
        self.imports: Dict[Tuple[str, str], Tuple[str, str]] = {}
        # (rel, top-level name) -> fid/cid defined in that module
        self.module_defs: Dict[Tuple[str, str], str] = {}
        # method name -> cids defining it (unique-name fallback)
        self.method_homes: Dict[str, List[str]] = {}

        for pf in self.files:
            self.modules[module_of(pf.rel)] = pf.rel
        for pf in self.files:
            self._index_defs(pf)
        for pf in self.files:
            self._index_imports(pf)
        self._link_classes()

    # -- definitions --------------------------------------------------------

    def _index_defs(self, pf: ParsedFile) -> None:
        def walk(node, stack: List[str], cls: Optional[str], parent_fid):
            for child in ast.iter_child_nodes(node):
                if isinstance(child, ast.ClassDef):
                    qn = ".".join(stack + [child.name])
                    ci = ClassInfo(pf, child, qn)
                    self.classes[ci.cid] = ci
                    if not stack:
                        self.module_defs[(pf.rel, child.name)] = ci.cid
                    walk(child, stack + [child.name], ci.cid, parent_fid)
                elif isinstance(child, FuncNode):
                    qn = ".".join(stack + [child.name])
                    fi = FuncInfo(pf, child, qn, cls, parent_fid)
                    self.functions[fi.fid] = fi
                    if not stack:
                        self.module_defs[(pf.rel, child.name)] = fi.fid
                    if cls is not None and self.classes[cls].node is node:
                        self.classes[cls].methods[child.name] = fi.fid
                    # nested defs: enclosing class no longer applies
                    walk(child, stack + [child.name], None, fi.fid)
                else:
                    walk(child, stack, cls, parent_fid)

        walk(pf.tree, [], None, None)

    # -- imports ------------------------------------------------------------

    def _resolve_module(self, modname: str) -> Optional[str]:
        if modname in self.modules:
            return modname
        return None

    def _index_imports(self, pf: ParsedFile) -> None:
        here = module_of(pf.rel)
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    target = self._resolve_module(alias.name)
                    if target:
                        local = alias.asname or alias.name.split(".")[0]
                        # `import a.b.c` binds `a`; only map exact-alias uses
                        if alias.asname or "." not in alias.name:
                            self.imports[(pf.rel, local)] = ("module", target)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    parts = here.split(".")
                    # `from . import x` in pkg/mod.py: level 1 = pkg
                    parts = parts[: len(parts) - node.level]
                    base = ".".join(parts + ([base] if base else []))
                for alias in node.names:
                    local = alias.asname or alias.name
                    submod = f"{base}.{alias.name}" if base else alias.name
                    if self._resolve_module(submod):
                        self.imports[(pf.rel, local)] = ("module", submod)
                        continue
                    src_rel = self.modules.get(base)
                    if src_rel is None:
                        continue
                    target = self.module_defs.get((src_rel, alias.name))
                    if target:
                        self.imports[(pf.rel, local)] = ("symbol", target)

    # -- class linking ------------------------------------------------------

    def _link_classes(self) -> None:
        for ci in self.classes.values():
            for base in ci.node.bases:
                resolved = self.resolve_name_target(ci.pf.rel, _dotted(base))
                if resolved in self.classes:
                    ci.bases.append(resolved)
            # class-body first-class assignments:
            #   _parse_bytes = BatchBlockMixin._parse_block
            for stmt in ci.node.body:
                if not (
                    isinstance(stmt, ast.Assign)
                    and len(stmt.targets) == 1
                    and isinstance(stmt.targets[0], ast.Name)
                ):
                    continue
                fid = self.resolve_name_target(
                    ci.pf.rel, _dotted(stmt.value)
                )
                if fid in self.functions:
                    ci.methods[stmt.targets[0].id] = fid
        for ci in self.classes.values():
            for name, fid in ci.methods.items():
                self.method_homes.setdefault(name, []).append(ci.cid)

    # -- lookups ------------------------------------------------------------

    def resolve_name_target(self, rel: str, dotted: str) -> Optional[str]:
        """Resolve a possibly-dotted name used in ``rel`` to a project
        fid/cid: local module def, imported symbol, or attribute chain
        through imported modules / project classes."""
        if not dotted:
            return None
        parts = dotted.split(".")
        head, rest = parts[0], parts[1:]
        target = self.module_defs.get((rel, head))
        kind = None
        if target is None:
            imp = self.imports.get((rel, head))
            if imp is None:
                return None
            kind, target = imp
        if not rest:
            return target
        if kind == "module" or target in self.modules:
            # walk module attributes: mod.sub.fn
            modname = target
            while rest:
                nxt = f"{modname}.{rest[0]}"
                if nxt in self.modules:
                    modname, rest = nxt, rest[1:]
                    continue
                src_rel = self.modules.get(modname)
                if src_rel is None:
                    return None
                return self.module_defs.get((src_rel, rest[0])) if len(
                    rest
                ) == 1 else None
            return None
        if target in self.classes and len(rest) == 1:
            return self.lookup_method(target, rest[0])
        return None

    def lookup_method(self, cid: str, name: str) -> Optional[str]:
        """Method resolution through project bases (MRO-ish, DFS)."""
        seen: Set[str] = set()
        stack = [cid]
        while stack:
            c = stack.pop(0)
            if c in seen or c not in self.classes:
                continue
            seen.add(c)
            ci = self.classes[c]
            if name in ci.methods:
                return ci.methods[name]
            stack.extend(ci.bases)
        return None

    def enclosing_class(self, fi: FuncInfo) -> Optional[ClassInfo]:
        return self.classes.get(fi.cls) if fi.cls else None
