"""Worklist taint propagator.

The engine runs a summary-based interprocedural analysis:

1. every function body is abstractly interpreted once, producing a
   :class:`Summary` — which params flow to the return value, whether the
   return is unconditionally tainted (the body called a *source*), and
   which *sink records* exist (a sink is either ``always`` hot, or
   conditional on a set of params being tainted);
2. a worklist iterates to fixpoint: when a callee's summary grows, its
   callers are re-interpreted, so taint crosses any number of call
   boundaries (store → protocol → node is three hops);
3. conditional sink records translate through call sites — the final
   finding carries the **full source→sink chain** of fids.

Taint values form a small lattice: ``deps`` (the current function's
params this value depends on — the symbolic half) plus ``tainted``
(definitely carries secret material — the concrete half, with an origin
description and the call chain it travelled). ``merge`` is pointwise
union; there is no widening because chains only grow along *new* call
edges and the call graph is finite.

What counts as source/sink/sanitizer is the policy's business
(:mod:`taint` builds the MPF7xx policy); the engine only knows the
lattice and the language.
"""
from __future__ import annotations

import ast
from typing import Dict, List, Optional, Tuple

from ..core import Finding
from .callgraph import CallGraph
from .symbols import FuncInfo, FuncNode, ProjectIndex, _dotted

EMPTY: frozenset = frozenset()


class TVal:
    """One abstract value."""

    __slots__ = ("deps", "tainted", "origin", "chain")

    def __init__(
        self,
        deps: frozenset = EMPTY,
        tainted: bool = False,
        origin: str = "",
        chain: Tuple[str, ...] = (),
    ):
        self.deps = deps
        self.tainted = tainted
        self.origin = origin
        self.chain = chain

    def merge(self, other: "TVal") -> "TVal":
        if other is CLEAN:
            return self
        if self is CLEAN:
            return other
        return TVal(
            self.deps | other.deps,
            self.tainted or other.tainted,
            self.origin or other.origin,
            self.chain or other.chain,
        )

    @property
    def hot(self) -> bool:
        return self.tainted or bool(self.deps)


CLEAN = TVal()


class SinkRec:
    """A sink inside some function: fires when ``always`` or when any
    param in ``param_deps`` receives tainted data from a caller."""

    __slots__ = (
        "kind", "detail", "line", "path", "symbol",
        "param_deps", "always", "origin", "chain",
    )

    def __init__(self, kind, detail, line, path, symbol,
                 param_deps, always, origin, chain):
        self.kind = kind
        self.detail = detail
        self.line = line
        self.path = path
        self.symbol = symbol
        self.param_deps = param_deps
        self.always = always
        self.origin = origin
        self.chain = chain  # fids from the sink's function down to the sink

    def ident(self):
        return (
            self.kind, self.detail, self.path, self.symbol,
            self.param_deps, self.always,
        )


class Summary:
    __slots__ = ("ret", "sinks")

    def __init__(self):
        self.ret = CLEAN
        self.sinks: List[SinkRec] = []


class Policy:
    """Source/sink/sanitizer decisions for one rule family."""

    rule_source = "MPF700"

    def source_call(self, fid: str) -> Optional[str]:
        """Origin label if calling ``fid`` yields secret material."""
        return None

    def source_name(self, name: str, fi: FuncInfo) -> Optional[str]:
        """Origin label if a bare name/attr is secret by naming."""
        return None

    def sanitizer(self, fid: Optional[str], dotted: str) -> bool:
        return False

    def sink(self, call: ast.Call, dotted: str, fi: FuncInfo,
             fid: Optional[str]) -> Optional[Tuple[str, str, str]]:
        """(rule, kind, detail) if ``call`` is a sink; the engine then
        checks which evaluated args are hot."""
        return None

    def raise_is_sink(self) -> Optional[Tuple[str, str]]:
        """(rule, kind) to treat tainted values in ``raise X(...)``
        arguments as a sink."""
        return None

    def cleaner_builtin(self, name: str) -> bool:
        return name in (
            "len", "type", "isinstance", "issubclass", "id", "hash",
            "range", "enumerate", "zip", "bool", "callable",
        )

    def public_attr(self, name: str) -> bool:
        """Attrs that stay clean even on a tainted base (``share.epoch``
        is public although ``share`` is secret material)."""
        return False


# container mutations that write argument taint into the receiver
_MUTATORS = {
    "append", "add", "extend", "update", "insert", "setdefault",
    "appendleft", "push",
}


class FlowEngine:
    def __init__(self, index: ProjectIndex, graph: CallGraph, policy: Policy):
        self.index = index
        self.graph = graph
        self.policy = policy
        self.summaries: Dict[str, Summary] = {}
        self.findings: Dict[str, Finding] = {}  # fingerprint -> finding

    # ------------------------------------------------------------------

    def run(self) -> List[Finding]:
        work: List[str] = list(self.index.functions)
        queued = set(work)
        rounds = 0
        while work:
            fid = work.pop()
            queued.discard(fid)
            rounds += 1
            if rounds > 20 * len(self.index.functions):  # safety valve
                break
            old = self.summaries.get(fid)
            new = self._interpret(fid)
            if old is None or self._grew(old, new):
                self.summaries[fid] = new
                for caller in self.graph.callers.get(fid, ()):
                    if caller not in queued:
                        queued.add(caller)
                        work.append(caller)
        return sorted(
            self.findings.values(),
            key=lambda f: (f.path, f.line, f.rule, f.key),
        )

    @staticmethod
    def _grew(old: Summary, new: Summary) -> bool:
        if (new.ret.deps - old.ret.deps) or (
            new.ret.tainted and not old.ret.tainted
        ):
            return True
        seen = {s.ident() for s in old.sinks}
        return any(s.ident() not in seen for s in new.sinks)

    # ------------------------------------------------------------------

    def _interpret(self, fid: str) -> Summary:
        fi = self.index.functions[fid]
        summ = Summary()
        env: Dict[str, TVal] = {}
        for p in fi.params:
            tv = TVal(deps=frozenset([p]))
            origin = None
            if p in fi.secret_params:
                origin = f"Secret[...] param '{p}'"
            else:
                origin = self.policy.source_name(p, fi)
            if origin:
                tv = TVal(frozenset([p]), True, origin, (fid,))
            env[p] = tv
        self._exec_block(fi.node.body, env, fi, summ)
        return summ

    # -- statements ----------------------------------------------------

    def _exec_block(self, stmts, env, fi: FuncInfo, summ: Summary) -> None:
        for st in stmts:
            self._exec(st, env, fi, summ)

    def _exec(self, st, env, fi: FuncInfo, summ: Summary) -> None:
        pf = fi.pf
        if isinstance(st, (ast.Assign, ast.AnnAssign, ast.AugAssign)):
            value = getattr(st, "value", None)
            if value is None:
                return
            tv = self._eval(value, env, fi, summ)
            if st.lineno in pf.declassified:
                tv = CLEAN
            targets = (
                st.targets if isinstance(st, ast.Assign) else [st.target]
            )
            for t in targets:
                self._bind(t, tv, env, fi, summ, aug=isinstance(st, ast.AugAssign))
        elif isinstance(st, ast.Expr):
            self._eval(st.value, env, fi, summ)
        elif isinstance(st, ast.Return):
            if st.value is not None:
                tv = self._eval(st.value, env, fi, summ)
                if fi.secret_return and not tv.tainted:
                    tv = tv.merge(
                        TVal(EMPTY, True, f"Secret[...] return of {fi.qualname}",
                             (fi.fid,))
                    )
                summ.ret = summ.ret.merge(tv)
        elif isinstance(st, ast.Raise):
            self._exec_raise(st, env, fi, summ)
        elif isinstance(st, (ast.If, ast.While)):
            self._eval(st.test, env, fi, summ)
            self._exec_block(st.body, env, fi, summ)
            self._exec_block(st.orelse, env, fi, summ)
        elif isinstance(st, (ast.For, ast.AsyncFor)):
            tv = self._eval(st.iter, env, fi, summ)
            self._bind(st.target, tv, env, fi, summ)
            self._exec_block(st.body, env, fi, summ)
            self._exec_block(st.orelse, env, fi, summ)
        elif isinstance(st, (ast.With, ast.AsyncWith)):
            for item in st.items:
                tv = self._eval(item.context_expr, env, fi, summ)
                if item.optional_vars is not None:
                    self._bind(item.optional_vars, tv, env, fi, summ)
            self._exec_block(st.body, env, fi, summ)
        elif isinstance(st, ast.Try):
            self._exec_block(st.body, env, fi, summ)
            for h in st.handlers:
                if h.name:
                    env[h.name] = CLEAN  # MPF702 fires at the raise site
                self._exec_block(h.body, env, fi, summ)
            self._exec_block(st.orelse, env, fi, summ)
            self._exec_block(st.finalbody, env, fi, summ)
        elif isinstance(st, FuncNode + (ast.ClassDef,)):
            return  # nested defs are analysed under their own fid
        elif isinstance(st, (ast.Assert,)):
            self._eval(st.test, env, fi, summ)
            if st.msg is not None:
                self._eval(st.msg, env, fi, summ)
        elif isinstance(st, ast.Delete):
            for t in st.targets:
                if isinstance(t, ast.Name):
                    env[t.id] = CLEAN
        elif isinstance(st, (ast.Match,)):
            self._eval(st.subject, env, fi, summ)
            for case in st.cases:
                self._exec_block(case.body, env, fi, summ)

    def _bind(self, target, tv: TVal, env, fi, summ, aug: bool = False) -> None:
        if isinstance(target, ast.Name):
            if aug:
                tv = tv.merge(env.get(target.id, CLEAN))
            env[target.id] = tv
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._bind(elt, tv, env, fi, summ)
        elif isinstance(target, ast.Starred):
            self._bind(target.value, tv, env, fi, summ)
        elif isinstance(target, ast.Attribute):
            base = _dotted(target)
            if base:  # self.x or obj.x — track as a scoped pseudo-name
                prev = env.get(base, CLEAN)
                env[base] = prev.merge(tv)
                # writing rep.x = secret makes the whole local object hot
                # (so `return rep` carries it); self stays exempt — methods
                # seed their own attr taint from source_name instead
                root = base.split(".", 1)[0]
                if base != root and root not in ("self", "cls"):
                    env[root] = env.get(root, CLEAN).merge(tv)
        elif isinstance(target, ast.Subscript):
            # dict/list round-trip: d[k] = secret taints d
            self._eval(target.slice, env, fi, summ)
            base = target.value
            name = (
                base.id if isinstance(base, ast.Name) else _dotted(base)
            )
            if name:
                env[name] = env.get(name, CLEAN).merge(tv)

    def _exec_raise(self, st: ast.Raise, env, fi, summ) -> None:
        spec = self.policy.raise_is_sink()
        if st.exc is None:
            return
        tv = self._eval(st.exc, env, fi, summ)
        if spec is None:
            return
        rule, kind = spec
        exc_name = ""
        if isinstance(st.exc, ast.Call):
            exc_name = _dotted(st.exc.func)
        if tv.hot and not fi.pf.is_suppressed(rule, st.lineno):
            self._record_sink(
                rule, kind, exc_name or "raise", st.lineno, tv, fi, summ
            )

    # -- expressions ---------------------------------------------------

    def _eval(self, node, env, fi: FuncInfo, summ: Summary) -> TVal:
        if isinstance(node, ast.Name):
            tv = env.get(node.id)
            if tv is not None:
                return tv
            origin = self.policy.source_name(node.id, fi)
            if origin:
                return TVal(EMPTY, True, origin, (fi.fid,))
            return CLEAN
        if isinstance(node, ast.Attribute):
            dotted = _dotted(node)
            if dotted and dotted in env:
                return env[dotted]
            if self.policy.public_attr(node.attr):
                self._eval(node.value, env, fi, summ)
                return CLEAN
            base = self._eval(node.value, env, fi, summ)
            origin = self.policy.source_name(node.attr, fi)
            if origin and not base.tainted:
                return base.merge(TVal(EMPTY, True, origin, (fi.fid,)))
            return base
        if isinstance(node, ast.Call):
            return self._eval_call(node, env, fi, summ)
        if isinstance(node, (ast.Constant, ast.Lambda)):
            return CLEAN
        if isinstance(node, ast.JoinedStr):
            out = CLEAN
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    out = out.merge(self._eval(v.value, env, fi, summ))
            return out
        if isinstance(node, ast.FormattedValue):
            return self._eval(node.value, env, fi, summ)
        if isinstance(node, (ast.BinOp,)):
            return self._eval(node.left, env, fi, summ).merge(
                self._eval(node.right, env, fi, summ)
            )
        if isinstance(node, ast.BoolOp):
            out = CLEAN
            for v in node.values:
                out = out.merge(self._eval(v, env, fi, summ))
            return out
        if isinstance(node, ast.UnaryOp):
            return self._eval(node.operand, env, fi, summ)
        if isinstance(node, ast.Compare):
            self._eval(node.left, env, fi, summ)
            for c in node.comparators:
                self._eval(c, env, fi, summ)
            return CLEAN  # a comparison result is a bool, not the secret
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            out = CLEAN
            for e in node.elts:
                out = out.merge(self._eval(e, env, fi, summ))
            return out
        if isinstance(node, ast.Dict):
            out = CLEAN
            for v in node.values:
                if v is not None:
                    out = out.merge(self._eval(v, env, fi, summ))
            return out
        if isinstance(node, ast.Subscript):
            self._eval(node.slice, env, fi, summ)
            return self._eval(node.value, env, fi, summ)
        if isinstance(node, ast.Starred):
            return self._eval(node.value, env, fi, summ)
        if isinstance(node, ast.IfExp):
            self._eval(node.test, env, fi, summ)
            return self._eval(node.body, env, fi, summ).merge(
                self._eval(node.orelse, env, fi, summ)
            )
        if isinstance(
            node, (ast.ListComp, ast.SetComp, ast.GeneratorExp, ast.DictComp)
        ):
            scope = dict(env)
            for gen in node.generators:
                tv = self._eval(gen.iter, scope, fi, summ)
                self._bind(gen.target, tv, scope, fi, summ)
                for cond in gen.ifs:
                    self._eval(cond, scope, fi, summ)
            if isinstance(node, ast.DictComp):
                return self._eval(node.key, scope, fi, summ).merge(
                    self._eval(node.value, scope, fi, summ)
                )
            return self._eval(node.elt, scope, fi, summ)
        if isinstance(node, (ast.Await, ast.YieldFrom)):
            return self._eval(node.value, env, fi, summ)
        if isinstance(node, ast.Yield):
            if node.value is not None:
                tv = self._eval(node.value, env, fi, summ)
                summ.ret = summ.ret.merge(tv)
                return tv
            return CLEAN
        if isinstance(node, ast.NamedExpr):
            tv = self._eval(node.value, env, fi, summ)
            self._bind(node.target, tv, env, fi, summ)
            return tv
        return CLEAN

    # -- calls -----------------------------------------------------------

    def _eval_call(self, call: ast.Call, env, fi: FuncInfo, summ) -> TVal:
        pol = self.policy
        dotted = _dotted(call.func)
        fid = self.graph.resolve_callee(fi, call.func)
        ctor = False
        if fid is not None and fid in self.index.classes:
            fid = self.index.lookup_method(fid, "__init__")
            ctor = True

        # evaluate arguments (and receiver) first
        arg_tvs: List[TVal] = [
            self._eval(a, env, fi, summ) for a in call.args
        ]
        kw_tvs: Dict[str, TVal] = {
            kw.arg: self._eval(kw.value, env, fi, summ)
            for kw in call.keywords
            if kw.arg is not None
        }
        star_kw = [
            self._eval(kw.value, env, fi, summ)
            for kw in call.keywords
            if kw.arg is None
        ]
        recv = CLEAN
        if isinstance(call.func, ast.Attribute):
            recv = self._eval(call.func.value, env, fi, summ)
        merged = recv
        for tv in arg_tvs + list(kw_tvs.values()) + star_kw:
            merged = merged.merge(tv)

        # sinks first: a call can be both sink and propagator
        sink = pol.sink(call, dotted, fi, fid)
        if sink is not None:
            rule, kind, detail = sink
            if merged.hot and not fi.pf.is_suppressed(rule, call.lineno):
                self._record_sink(
                    rule, kind, detail, call.lineno, merged, fi, summ
                )

        if pol.sanitizer(fid, dotted):
            return CLEAN
        if fid is not None:
            origin = pol.source_call(fid)
            if origin is not None:
                return TVal(EMPTY, True, origin, (fi.fid, fid))
            callee = self.index.functions.get(fid)
            if callee is not None:
                return self._apply_summary(
                    fid, callee, call, arg_tvs, kw_tvs, recv, fi, summ,
                    ctor=ctor,
                )
        if ctor and fid is None:
            # dataclass-style ctor (project class, no explicit __init__):
            # a secret keyword is stored under its own field name and any
            # read re-taints through the taxonomy, so keep the holder
            # object clean instead of smearing every field —
            # cfg = SoakConfig(seed=...) must not taint cfg.n_nodes
            out = recv
            for tv in arg_tvs + star_kw:
                out = out.merge(tv)
            for key, tv in kw_tvs.items():
                if not pol.source_name(key, fi):
                    out = out.merge(tv)
            return out

        # unresolved call: conservatively propagate args + receiver,
        # minus known-clean builtins
        name = dotted.rsplit(".", 1)[-1] if dotted else ""
        if "." not in dotted and pol.cleaner_builtin(name):
            return CLEAN
        # container mutation: d.append(secret) / d.update(...) writes the
        # argument taint back into the receiver binding
        if (
            isinstance(call.func, ast.Attribute)
            and call.func.attr in _MUTATORS
        ):
            args_only = CLEAN
            for tv in arg_tvs + list(kw_tvs.values()) + star_kw:
                args_only = args_only.merge(tv)
            if args_only.hot:
                base = _dotted(call.func.value)
                if base:
                    env[base] = env.get(base, CLEAN).merge(args_only)
                    root = base.split(".", 1)[0]
                    if base != root and root not in ("self", "cls"):
                        env[root] = env.get(root, CLEAN).merge(args_only)
        return merged

    def _apply_summary(
        self, fid, callee: FuncInfo, call, arg_tvs, kw_tvs, recv, fi, summ,
        ctor: bool = False,
    ) -> TVal:
        cs = self.summaries.get(fid)
        # map callee params -> caller TVals
        pmap: Dict[str, TVal] = {}
        params = list(callee.params)
        if ctor and params[:1] == ["self"]:
            # C(...) binds a fresh object to self, not the first argument
            pmap[params[0]] = CLEAN
            pos_params = params[1:]
        else:
            recv_style = (
                isinstance(call.func, ast.Attribute)
                and params[:1] in (["self"], ["cls"])
                and self.index.resolve_name_target(
                    fi.pf.rel, _dotted(call.func.value)
                ) not in self.index.classes
            )
            if recv_style:
                pmap[params[0]] = recv
                pos_params = params[1:]
            else:
                pos_params = params
        for p, tv in zip(pos_params, arg_tvs):
            pmap[p] = tv
        for k, tv in kw_tvs.items():
            if k in callee.params:
                pmap[k] = tv

        if cs is None:
            out = CLEAN
            for tv in pmap.values():
                out = out.merge(tv)
            return out

        # conditional sinks in the callee fire when we pass hot args
        for rec in cs.sinks:
            if rec.always:
                continue
            hit = CLEAN
            for p in rec.param_deps:
                tv = pmap.get(p)
                if tv is not None and tv.hot:
                    hit = hit.merge(tv)
            if not hit.hot:
                continue
            if fi.pf.is_suppressed(rec.kind, call.lineno):
                continue
            if hit.tainted:
                self._emit(rec, hit, via=fi)
            else:
                # still symbolic: lift the sink record into our summary
                lifted = hit.deps - {"self", "cls"}
                if lifted:
                    summ.sinks.append(
                        SinkRec(
                            rec.kind, rec.detail, rec.line, rec.path,
                            rec.symbol, lifted, False, rec.origin,
                            (fi.fid,) + rec.chain,
                        )
                    )

        # return taint
        out = CLEAN
        if cs.ret.tainted:
            out = TVal(
                EMPTY, True, cs.ret.origin,
                (fi.fid,) + (cs.ret.chain or (fid,)),
            )
        for p in cs.ret.deps:
            tv = pmap.get(p)
            if tv is not None:
                if tv.tainted:
                    out = out.merge(
                        TVal(tv.deps, True, tv.origin, tv.chain or (fi.fid,))
                    )
                else:
                    out = out.merge(tv)
        if callee.secret_return and not out.tainted:
            out = out.merge(
                TVal(EMPTY, True,
                     f"Secret[...] return of {callee.qualname}",
                     (fi.fid, fid))
            )
        return out

    # -- findings --------------------------------------------------------

    def _record_sink(self, rule, kind, detail, line, tv: TVal, fi, summ):
        rec = SinkRec(
            rule, detail, line, fi.pf.rel, fi.qualname,
            tv.deps, tv.tainted, tv.origin, (fi.fid,),
        )
        if tv.tainted:
            self._emit(rec, tv, via=None)
        # param-conditional: expose to callers too (an in-body source
        # already fired above; both can be true for merged values).
        # `self`/`cls` are excluded — "any caller holding a tainted object
        # reaches every sink in its methods" drowns real chains in noise;
        # attribute sources inside methods still fire directly.
        deps = tv.deps - {"self", "cls"}
        if deps:
            summ.sinks.append(
                SinkRec(rule, detail, line, fi.pf.rel, fi.qualname,
                        deps, False, tv.origin, (fi.fid,))
            )
        _ = kind

    def _emit(self, rec: SinkRec, tv: TVal, via: Optional[FuncInfo]):
        chain = tuple(tv.chain)
        for fid in rec.chain:
            if not chain or chain[-1] != fid:
                chain = chain + (fid,)
        pretty = " -> ".join(
            self.index.functions[f].qualname
            if f in self.index.functions
            else f
            for f in chain
        )
        origin = tv.origin or rec.origin or "secret source"
        key = f"{rec.detail}<-{_origin_token(origin)}"
        f = Finding(
            rule=rec.kind,
            path=rec.path,
            line=rec.line,
            symbol=rec.symbol,
            key=key,
            message=(
                f"secret data ({origin}) reaches {rec.detail}"
                f" [chain: {pretty}]"
            ),
        )
        self.findings.setdefault(f.fingerprint, f)
        _ = via


def _origin_token(origin: str) -> str:
    """Compress an origin description into a stable fingerprint token."""
    for ch in "'\"":
        origin = origin.replace(ch, "")
    return origin.replace(" ", "_")[:48]
