"""MPL4xx — jit/retrace hazards.

The perf work in PRs 2 and 5 is predicated on jit bodies staying
device-resident: one host sync inside a compiled region serializes the
pipeline, and one trace-dependent Python branch silently recompiles per
batch shape. Both are invisible in tests (CPU jit hides the cost) and
expensive on the accelerator, so they are linted instead.

MPL401  host-side numpy / .item() / scalar coercion of a traced value
        inside a ``@jax.jit`` body. np.* calls whose arguments reference
        no traced parameter (e.g. a domain tag built with np.frombuffer
        from a bytes literal and a loop index) are trace-time constant
        folding, not host syncs, and are NOT flagged here — the
        large-constant executable-bloat class they can cause belongs to
        MPS903 (analysis/shape), which sizes them.
MPL402  Python ``if``/``while`` on a non-static parameter inside a jit
        body — shape/dtype/ndim attribute tests are exempt (static under
        tracing); everything else either crashes or retraces.

Detection is lexical: a function is "a jit body" when its decorator list
contains ``jax.jit``/``jit`` or ``functools.partial(jax.jit, ...)``;
static parameters come from ``static_argnames``/``static_argnums``.
"""
from __future__ import annotations

import ast
from typing import Iterator, Optional, Set, Tuple

from ..core import Finding, LintContext, ParsedFile, Rule, dotted_name

_SCOPES = ("mpcium_tpu/engine/", "mpcium_tpu/ops/", "mpcium_tpu/protocol/")

_HOST_ROOTS = ("np.", "numpy.")
_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_COERCIONS = {"int", "float", "bool"}
_STATIC_ATTRS = {"shape", "ndim", "dtype", "size"}


def _in_scope(rel: str) -> bool:
    return any(rel.startswith(s) for s in _SCOPES)


def _jit_static_params(
    fn: ast.FunctionDef | ast.AsyncFunctionDef,
) -> Optional[Set[str]]:
    """None when ``fn`` is not jit-decorated; otherwise the set of
    parameter names marked static."""
    params = [a.arg for a in fn.args.posonlyargs + fn.args.args]
    for dec in fn.decorator_list:
        name = dotted_name(dec)
        if name in ("jax.jit", "jit"):
            return set()
        if isinstance(dec, ast.Call):
            cname = dotted_name(dec.func)
            inner = dotted_name(dec.args[0]) if dec.args else ""
            if cname.endswith("partial") and inner in ("jax.jit", "jit"):
                static: Set[str] = set()
                for kw in dec.keywords:
                    if kw.arg == "static_argnames":
                        for c in ast.walk(kw.value):
                            if isinstance(c, ast.Constant) and isinstance(
                                c.value, str
                            ):
                                static.add(c.value)
                    elif kw.arg == "static_argnums":
                        for c in ast.walk(kw.value):
                            if isinstance(c, ast.Constant) and isinstance(
                                c.value, int
                            ):
                                if 0 <= c.value < len(params):
                                    static.add(params[c.value])
                return static
            if cname in ("jax.jit", "jit"):
                return set()
    return None


def _jit_functions(
    pf: ParsedFile,
) -> Iterator[Tuple[ast.FunctionDef | ast.AsyncFunctionDef, Set[str], Set[str]]]:
    """(fn, traced_params, static_params) for every jit body in the file."""
    for node in ast.walk(pf.tree):
        if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        static = _jit_static_params(node)
        if static is None:
            continue
        params = {
            a.arg
            for a in node.args.posonlyargs + node.args.args + node.args.kwonlyargs
        }
        yield node, params - static - {"self"}, static


class HostSyncInJit(Rule):
    id = "MPL401"
    summary = "no host numpy / .item() / scalar coercion inside jit bodies"

    def applies(self, rel: str) -> bool:
        return _in_scope(rel)

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        for fn, traced, _static in _jit_functions(pf):
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                name = dotted_name(node.func)
                offense = ""
                if name and name.startswith(_HOST_ROOTS):
                    arg_ids = {
                        n.id
                        for a in (
                            list(node.args)
                            + [kw.value for kw in node.keywords]
                        )
                        for n in ast.walk(a)
                        if isinstance(n, ast.Name)
                    }
                    # np.* over literals/loop indices only runs at trace
                    # time (constant folding) — MPS903 owns that class
                    if arg_ids & traced:
                        offense = name
                elif (
                    isinstance(node.func, ast.Attribute)
                    and node.func.attr in _SYNC_METHODS
                ):
                    offense = f".{node.func.attr}()"
                elif name in _COERCIONS and node.args:
                    arg_ids = {
                        n.id
                        for n in ast.walk(node.args[0])
                        if isinstance(n, ast.Name)
                    }
                    if arg_ids & traced:
                        offense = f"{name}(<traced>)"
                if not offense:
                    continue
                yield Finding(
                    rule=self.id,
                    path=pf.rel,
                    line=node.lineno,
                    symbol=f"{pf.symbol_of(fn)}.{fn.name}".lstrip("."),
                    key=offense,
                    message=(
                        f"{offense} inside jit body {fn.name!r} — host sync "
                        f"or per-trace host work; hoist out of the compiled "
                        f"region (baseline it only if it is provably "
                        f"trace-time-constant)"
                    ),
                )


class TracedBranchInJit(Rule):
    id = "MPL402"
    summary = "no Python branching on traced values inside jit bodies"

    def applies(self, rel: str) -> bool:
        return _in_scope(rel)

    def _traced_names_in_test(self, test: ast.AST, traced: Set[str]) -> Set[str]:
        """Names of traced params used *by value* in a test. Attribute
        access limited to shape/ndim/dtype/size is static and exempt."""
        hits: Set[str] = set()

        def walk(node: ast.AST) -> None:
            if (
                isinstance(node, ast.Attribute)
                and node.attr in _STATIC_ATTRS
            ):
                return  # x.shape[...] — static under tracing
            if isinstance(node, ast.Call):
                fname = dotted_name(node.func)
                if fname == "len" or fname == "isinstance":
                    return
            if isinstance(node, ast.Name) and node.id in traced:
                hits.add(node.id)
            for child in ast.iter_child_nodes(node):
                walk(child)

        walk(test)
        return hits

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        for fn, traced, _static in _jit_functions(pf):
            if not traced:
                continue
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                hits = self._traced_names_in_test(node.test, traced)
                for ident in sorted(hits):
                    yield Finding(
                        rule=self.id,
                        path=pf.rel,
                        line=node.lineno,
                        symbol=f"{pf.symbol_of(fn)}.{fn.name}".lstrip("."),
                        key=ident,
                        message=(
                            f"Python branch on traced value {ident!r} in jit "
                            f"body {fn.name!r} — use jnp.where/lax.cond, or "
                            f"mark the argument static"
                        ),
                    )
