"""Rule plugin registry. A rule family is one module; adding a family =
adding a module here. Keep construction cheap — the CLI and the tier-1
gate build a fresh rule set per sweep (rules may hold cross-file state)."""
from __future__ import annotations

from typing import List

from ..core import Rule
from .determinism import DictOrderIteration, ForbiddenEntropyCall
from .hygiene import BareExcept, MutableDefaultArg, UnusedImport
from .jit_hazards import HostSyncInJit, TracedBranchInJit
from .lock_discipline import LockOrderInversion, UnguardedLockedField
from .secret_hygiene import SecretCompare, SecretInException, SecretToLog
from .wire_thread import UnmanagedThread, WireVersionRoundTrip


def all_rules() -> List[Rule]:
    return [
        SecretToLog(),
        SecretInException(),
        SecretCompare(),
        ForbiddenEntropyCall(),
        DictOrderIteration(),
        UnguardedLockedField(),
        LockOrderInversion(),
        HostSyncInJit(),
        TracedBranchInJit(),
        WireVersionRoundTrip(),
        UnmanagedThread(),
        BareExcept(),
        MutableDefaultArg(),
        UnusedImport(),
    ]


def rule_catalog() -> List[Rule]:
    """Stable listing for ``mpclint --list-rules`` and the docs."""
    return all_rules()
