"""MPL3xx — lock discipline.

PR 4 found two latent session races by *drilling*; this family finds the
shape statically:

MPL301  a field declared ``@locked_by("_lock", "_started", ...)`` is
        written outside ``with self._lock:`` (the ``_started``
        publish-before-start race is exactly this shape). ``__init__``
        is exempt (unpublished object); helper methods whose whole body
        runs under the lock are marked ``# mpclint: holds=_lock`` on
        their ``def`` line.
MPL302  the package-wide lock-acquisition graph has a cycle (lock-order
        inversion). Edges come from lexically nested ``with self.X:``
        blocks and from same-class calls made while a lock is held into
        methods that acquire another lock. Analysis is lexical: code
        that releases a lock before calling out (e.g. the timing wheel
        running callbacks after its ``with`` block closes) creates no
        edge — which is the pattern this repo uses deliberately.
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, List, Set, Tuple

from ..core import Finding, LintContext, ParsedFile, Rule, self_attr

_MUTATORS = {
    "append",
    "add",
    "extend",
    "update",
    "pop",
    "popitem",
    "remove",
    "discard",
    "clear",
    "setdefault",
    "insert",
}

_LOCKISH = ("lock", "cond", "mutex")


def _is_lockish(attr: str) -> bool:
    a = attr.lower()
    return any(t in a for t in _LOCKISH)


def _locked_by_decl(cls: ast.ClassDef) -> Dict[str, Set[str]]:
    """Parse ``@locked_by("_lock", "_a", "_b")`` decorators (stackable)."""
    decls: Dict[str, Set[str]] = {}
    for dec in cls.decorator_list:
        if not isinstance(dec, ast.Call):
            continue
        fname = dec.func
        name = (
            fname.id
            if isinstance(fname, ast.Name)
            else fname.attr
            if isinstance(fname, ast.Attribute)
            else ""
        )
        if name != "locked_by" or not dec.args:
            continue
        vals = [
            a.value
            for a in dec.args
            if isinstance(a, ast.Constant) and isinstance(a.value, str)
        ]
        if len(vals) >= 2:
            decls.setdefault(vals[0], set()).update(vals[1:])
    return decls


class _MethodScan(ast.NodeVisitor):
    """One method: which guarded fields are written while which of the
    class's locks are (lexically) held."""

    def __init__(self, lock_names: Set[str], held0: Set[str]):
        self.lock_names = lock_names
        self.held: Set[str] = set(held0)
        # (field, lineno, held_at_that_point)
        self.writes: List[Tuple[str, int, Set[str]]] = []
        # lock -> locks acquired while it is held (for MPL302)
        self.nested: List[Tuple[str, str, int]] = []
        # lock -> same-class methods called while it is held
        self.calls_under: List[Tuple[str, str, int]] = []
        # every same-class call: (method, locks_held_at_site, lineno) —
        # MPL301 uses this for the one-level delegation exemption
        self.self_calls: List[Tuple[str, Set[str], int]] = []

    def visit_With(self, node: ast.With) -> None:
        acquired: List[str] = []
        for item in node.items:
            attr = self_attr(item.context_expr)
            if attr is None and isinstance(item.context_expr, ast.Call):
                # `with self._lock:` vs `with self._cond:` vs cond.wait()
                attr = self_attr(item.context_expr.func)
            if attr and (attr in self.lock_names or _is_lockish(attr)):
                acquired.append(attr)
        for a in acquired:
            for h in self.held:
                if h != a:
                    self.nested.append((h, a, node.lineno))
        self.held |= set(acquired)
        for stmt in node.body:
            self.visit(stmt)
        self.held -= set(acquired)
        # type comment/withitems need no further walk

    def _record_write(self, field: str, lineno: int) -> None:
        self.writes.append((field, lineno, set(self.held)))

    def visit_Assign(self, node: ast.Assign) -> None:
        for t in node.targets:
            f = self_attr(t)
            if f:
                self._record_write(f, node.lineno)
            elif isinstance(t, ast.Tuple):
                for el in t.elts:
                    f = self_attr(el)
                    if f:
                        self._record_write(f, node.lineno)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        f = self_attr(node.target)
        if f:
            self._record_write(f, node.lineno)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        f = self_attr(node.target)
        if f and node.value is not None:
            self._record_write(f, node.lineno)
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        func = node.func
        if isinstance(func, ast.Attribute):
            # self._buffer.append(...) — a write to the container field
            if func.attr in _MUTATORS:
                f = self_attr(func.value)
                if f:
                    self._record_write(f, node.lineno)
            # self.other_method() while holding a lock → call edge
            f = self_attr(func)
            if f:
                self.self_calls.append((f, set(self.held), node.lineno))
                for h in self.held:
                    self.calls_under.append((h, f, node.lineno))
        self.generic_visit(node)

    # nested defs get their own scan via the class walker; don't descend
    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        return

    def visit_AsyncFunctionDef(self, node: ast.AsyncFunctionDef) -> None:
        return

    def visit_Lambda(self, node: ast.Lambda) -> None:
        return


class UnguardedLockedField(Rule):
    id = "MPL301"
    summary = "@locked_by fields must only be written under their lock"

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        for cls in ast.walk(pf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            decls = _locked_by_decl(cls)
            if not decls:
                continue
            lock_names = set(decls)
            field_to_lock: Dict[str, str] = {
                f: lock for lock, fields in decls.items() for f in fields
            }
            methods: Dict[str, ast.AST] = {}
            scans: Dict[str, _MethodScan] = {}
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                held0: Set[str] = set()
                holds = pf.holds.get(meth.lineno)
                if holds:
                    held0.add(holds)
                scan = _MethodScan(lock_names, held0)
                for stmt in meth.body:
                    scan.visit(stmt)
                methods[meth.name] = meth
                scans[meth.name] = scan
            # method -> (caller, locks held at each same-class call site)
            call_sites: Dict[str, List[Tuple[str, Set[str]]]] = {}
            for caller, scan in scans.items():
                for callee, held_at, _line in scan.self_calls:
                    call_sites.setdefault(callee, []).append((caller, held_at))
            for name, meth in methods.items():
                if name == "__init__":
                    continue
                for fieldname, lineno, held in scans[name].writes:
                    lock = field_to_lock.get(fieldname)
                    if lock is None or lock in held:
                        continue
                    # one-level delegation: a private helper whose every
                    # same-class call site already holds the lock is
                    # effectively '# mpclint: holds=<lock>' — the lexical
                    # held-set at the call site is what counts, so the
                    # exemption does not chain through a second helper
                    sites = [
                        h
                        for caller, h in call_sites.get(name, ())
                        if caller != name
                    ]
                    if (
                        name.startswith("_")
                        and sites
                        and all(lock in h for h in sites)
                    ):
                        continue
                    yield Finding(
                        rule=self.id,
                        path=pf.rel,
                        line=lineno,
                        symbol=f"{pf.symbol_of(meth)}.{meth.name}".lstrip("."),
                        key=fieldname,
                        message=(
                            f"write to {fieldname!r} outside 'with "
                            f"self.{lock}:' (declared @locked_by); hold the "
                            f"lock or mark the method '# mpclint: "
                            f"holds={lock}'"
                        ),
                    )


class LockOrderInversion(Rule):
    id = "MPL302"
    summary = "lock-acquisition graph must stay acyclic"

    def __init__(self) -> None:
        # "Class.lock" -> {"Class.lock2": (path, line)}
        self._edges: Dict[str, Dict[str, Tuple[str, int]]] = {}

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        for cls in ast.walk(pf.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            # per-method: nested with-blocks + calls made under a lock
            acquires: Dict[str, Set[str]] = {}  # method -> locks it takes
            scans: Dict[str, _MethodScan] = {}
            for meth in cls.body:
                if not isinstance(meth, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    continue
                held0: Set[str] = set()
                holds = pf.holds.get(meth.lineno)
                if holds:
                    held0.add(holds)
                scan = _MethodScan(set(), held0)
                for stmt in meth.body:
                    scan.visit(stmt)
                scans[meth.name] = scan
                taken = {a for (_h, a, _l) in scan.nested}
                taken |= {
                    a
                    for (_f, _l, hs) in scan.writes
                    for a in hs
                }
                # locks this method acquires lexically anywhere
                acquires[meth.name] = _all_acquired(meth)
            qual = lambda lock: f"{cls.name}.{lock}"  # noqa: E731
            for scan in scans.values():
                for held, acq, line in scan.nested:
                    self._edges.setdefault(qual(held), {}).setdefault(
                        qual(acq), (pf.rel, line)
                    )
                for held, callee, line in scan.calls_under:
                    for acq in acquires.get(callee, ()):
                        if acq != held:
                            self._edges.setdefault(qual(held), {}).setdefault(
                                qual(acq), (pf.rel, line)
                            )
        return iter(())

    def finalize(self, ctx: LintContext) -> Iterator[Finding]:
        # DFS cycle detection over the accumulated graph
        WHITE, GRAY, BLACK = 0, 1, 2
        color: Dict[str, int] = {}
        stack: List[str] = []
        cycles: List[List[str]] = []

        def dfs(n: str) -> None:
            color[n] = GRAY
            stack.append(n)
            for m in self._edges.get(n, {}):
                c = color.get(m, WHITE)
                if c == WHITE:
                    dfs(m)
                elif c == GRAY:
                    cycles.append(stack[stack.index(m) :] + [m])
            stack.pop()
            color[n] = BLACK

        for n in sorted(self._edges):
            if color.get(n, WHITE) == WHITE:
                dfs(n)
        seen: Set[Tuple[str, ...]] = set()
        for cyc in cycles:
            canon = tuple(sorted(set(cyc)))
            if canon in seen:
                continue
            seen.add(canon)
            a, b = cyc[0], cyc[1 % len(cyc)]
            path, line = self._edges[a][b]
            yield Finding(
                rule=self.id,
                path=path,
                line=line,
                symbol="",
                key="->".join(cyc),
                message=(
                    f"lock-order inversion: {' -> '.join(cyc)} — impose a "
                    f"global order or release before calling out"
                ),
            )


def _all_acquired(meth: ast.AST) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(meth):
        if isinstance(node, ast.With):
            for item in node.items:
                attr = self_attr(item.context_expr)
                if attr and _is_lockish(attr):
                    out.add(attr)
    return out
