"""MPL6xx — general hygiene.

The container has no ruff/mypy, so the three ruff-class defects this
repo actually produces are enforced natively (the pyproject configs
still exist for environments that do have the tools — see
STATIC_ANALYSIS.md):

MPL601  bare ``except:`` — swallows KeyboardInterrupt/SystemExit and
        masks faults the chaos drills are supposed to surface
MPL602  mutable default argument
MPL603  unused import (skipped for ``__init__.py`` re-export modules)
"""
from __future__ import annotations

import ast
from typing import Dict, Iterator, Set

from ..core import Finding, LintContext, ParsedFile, Rule


class BareExcept(Rule):
    id = "MPL601"
    summary = "no bare except: clauses"

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.ExceptHandler) and node.type is None:
                yield Finding(
                    rule=self.id,
                    path=pf.rel,
                    line=node.lineno,
                    symbol=pf.symbol_of(node),
                    key=f"L{node.lineno // 50}",  # coarse bucket, survives small drift
                    message=(
                        "bare 'except:' also catches KeyboardInterrupt/"
                        "SystemExit — name the exceptions (or 'except "
                        "Exception:' at worst)"
                    ),
                )


class MutableDefaultArg(Rule):
    id = "MPL602"
    summary = "no mutable default arguments"

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        for fn in ast.walk(pf.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            args = fn.args
            pos = args.posonlyargs + args.args
            defaults = args.defaults
            pairs = list(zip(pos[len(pos) - len(defaults) :], defaults))
            pairs += [
                (a, d)
                for a, d in zip(args.kwonlyargs, args.kw_defaults)
                if d is not None
            ]
            for arg, default in pairs:
                bad = isinstance(default, (ast.List, ast.Dict, ast.Set)) or (
                    isinstance(default, ast.Call)
                    and isinstance(default.func, ast.Name)
                    and default.func.id in ("list", "dict", "set", "bytearray")
                )
                if bad:
                    yield Finding(
                        rule=self.id,
                        path=pf.rel,
                        line=fn.lineno,
                        symbol=f"{pf.symbol_of(fn)}.{fn.name}".lstrip("."),
                        key=arg.arg,
                        message=(
                            f"mutable default for {arg.arg!r} is shared "
                            f"across calls — default to None and build "
                            f"inside"
                        ),
                    )


class UnusedImport(Rule):
    id = "MPL603"
    summary = "no unused imports"

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        if pf.rel.endswith("__init__.py"):  # re-export surface
            return
        imported: Dict[str, int] = {}  # bound name -> lineno
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    name = alias.asname or alias.name.split(".")[0]
                    imported[name] = node.lineno
            elif isinstance(node, ast.ImportFrom):
                if node.module == "__future__":
                    continue
                for alias in node.names:
                    if alias.name == "*":
                        continue
                    imported[alias.asname or alias.name] = node.lineno
        if not imported:
            return
        used: Set[str] = set()
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Name):
                used.add(node.id)
            elif isinstance(node, ast.Attribute):
                pass  # root Name is walked separately
        # names referenced in __all__ or in string annotations count
        for node in ast.walk(pf.tree):
            if isinstance(node, ast.Constant) and isinstance(node.value, str):
                used.add(node.value)
        for name, lineno in sorted(imported.items()):
            if name in used:
                continue
            yield Finding(
                rule=self.id,
                path=pf.rel,
                line=lineno,
                symbol="",
                key=name,
                message=f"import {name!r} is unused",
            )
