"""MPL2xx — determinism.

The fault DSL's replay guarantee (faults/plan.py: every probabilistic
decision is a pure PRF of seed/rule/message) and the WAL's bit-identical
resume (store/session_wal.py: checkpointed payloads are re-sent, never
re-derived) both collapse if protocol code consults ambient entropy or
wall-clock time, or lets dict iteration order pick who hears what first.

MPL201  time.time()/random.*/os.urandom inside decision paths
MPL202  dict-order iteration over a peer set (sort it)

Scope: ``faults/plan.py`` and everything under ``protocol/``. Protocol
randomness is legitimate at round *start* — but it must come from the
party's own seeded/checkpointed source, never from module-level
``random`` or wall-clock; ``secrets``-based key material generation
lives in keygen paths and is checkpointed before routing, so it is not
banned here (the WAL re-sends, it never re-derives).
"""
from __future__ import annotations

import ast
from typing import Iterator

from ..core import Finding, LintContext, ParsedFile, Rule, dotted_name

_FORBIDDEN_CALLS = {
    "time.time": "wall-clock in a decision path breaks replay",
    "os.urandom": "ambient entropy breaks seed-determinism",
    "uuid.uuid4": "ambient entropy breaks seed-determinism",
}
_FORBIDDEN_ROOTS = {
    "random": "module-level random.* draws are interleaving-dependent",
    "np.random": "np.random.* draws are interleaving-dependent",
    "numpy.random": "np.random.* draws are interleaving-dependent",
}

_SCOPES = ("mpcium_tpu/faults/plan.py", "mpcium_tpu/protocol/")

# dict-named peer sets whose iteration order is a protocol decision
_PEERISH = {"peers", "participants", "parties", "members", "hellos", "peer_ids"}


def _in_scope(rel: str) -> bool:
    return rel.startswith(_SCOPES[1]) or rel == _SCOPES[0]


class ForbiddenEntropyCall(Rule):
    id = "MPL201"
    summary = "no wall-clock/ambient entropy in protocol/fault decision paths"

    def applies(self, rel: str) -> bool:
        return _in_scope(rel)

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func)
            if not name:
                continue
            why = _FORBIDDEN_CALLS.get(name)
            if why is None:
                for root, root_why in _FORBIDDEN_ROOTS.items():
                    if name.startswith(root + "."):
                        why = root_why
                        break
            if why is None:
                continue
            yield Finding(
                rule=self.id,
                path=pf.rel,
                line=node.lineno,
                symbol=pf.symbol_of(node),
                key=name,
                message=f"{name}() forbidden here: {why}",
            )


class DictOrderIteration(Rule):
    id = "MPL202"
    summary = "peer-set iteration must be sorted (dict order is a bug)"

    def applies(self, rel: str) -> bool:
        return _in_scope(rel)

    def _peerish_iter(self, it: ast.AST) -> str:
        """The peer-set identifier iterated over, or ''. `sorted(...)`
        wrappers make the iteration deterministic and pass."""
        target = it
        if isinstance(target, ast.Call):
            fname = dotted_name(target.func)
            if fname == "sorted" or fname.endswith(".sorted"):
                return ""
            # peers.keys() / parties.values() / parties.items()
            if isinstance(target.func, ast.Attribute) and target.func.attr in (
                "keys",
                "values",
                "items",
            ):
                target = target.func.value
            else:
                return ""
        if isinstance(target, ast.Name) and target.id in _PEERISH:
            return target.id
        if isinstance(target, ast.Attribute) and target.attr.lstrip("_") in _PEERISH:
            return target.attr
        return ""

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        for node in ast.walk(pf.tree):
            iters = []
            if isinstance(node, (ast.For, ast.AsyncFor)):
                iters = [node.iter]
            elif isinstance(
                node, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
            ):
                iters = [g.iter for g in node.generators]
            for it in iters:
                ident = self._peerish_iter(it)
                if not ident:
                    continue
                yield Finding(
                    rule=self.id,
                    path=pf.rel,
                    line=getattr(it, "lineno", node.lineno),
                    symbol=pf.symbol_of(node),
                    key=ident,
                    message=(
                        f"iteration over peer set {ident!r} in dict order — "
                        f"wrap in sorted(...) so every member walks peers "
                        f"identically"
                    ),
                )
