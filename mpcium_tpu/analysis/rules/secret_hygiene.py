"""MPL1xx — secret hygiene.

Targets the failure mode SECURITY.md's secret-handling section worries
about: key shares, WAL AEAD keys, OT pads, signing nonces or identity
private keys reaching a log line, an exception string (tracebacks get
shipped to log aggregators), or a timing-unsafe comparison.

MPL101  secret identifier flows into a logging call
MPL102  secret identifier interpolated into a raised exception message
MPL103  == / != on compare-sensitive material (use hmac.compare_digest)
"""
from __future__ import annotations

import ast
from typing import Iterator, Set

from ..core import Finding, LintContext, ParsedFile, Rule, dotted_name
from ..taxonomy import is_compare_sensitive, is_secret_name

_LOG_FUNCS = {
    "debug",
    "info",
    "warn",
    "warning",
    "error",
    "fatal",
    "critical",
    "exception",
    "log",
}
_LOG_OBJECTS = {"log", "logger", "logging", "_logger"}


def _is_log_call(call: ast.Call) -> bool:
    f = call.func
    if not isinstance(f, ast.Attribute) or f.attr not in _LOG_FUNCS:
        return False
    root = f.value
    # log.info(...), self.log.info(...), mpcium_tpu.utils.log.info(...)
    name = dotted_name(root)
    last = name.rsplit(".", 1)[-1] if name else ""
    return last in _LOG_OBJECTS


def _secret_names_in(node: ast.AST, extra: Set[str]) -> Iterator[ast.AST]:
    """Yield Name/Attribute nodes under ``node`` whose identifier is
    secret. ``x.hex()`` / ``repr(x)`` / f-string wrappers are walked
    through naturally by ast.walk."""
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name) and is_secret_name(sub.id, extra):
            yield sub
        elif isinstance(sub, ast.Attribute) and is_secret_name(sub.attr, extra):
            yield sub


class SecretToLog(Rule):
    id = "MPL101"
    summary = "secret material must not flow into logging calls"

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        extra = pf.extra_secrets
        for node in ast.walk(pf.tree):
            if not (isinstance(node, ast.Call) and _is_log_call(node)):
                continue
            exprs = list(node.args) + [kw.value for kw in node.keywords]
            # a secret-named KEYWORD with a benign value is still a leak
            # vector (log.info("x", share=len(s)) is fine; share=s is not)
            # — only the value expression decides.
            hit_names: Set[str] = set()
            for e in exprs:
                for s in _secret_names_in(e, extra):
                    ident = s.id if isinstance(s, ast.Name) else s.attr
                    hit_names.add(ident)
            for ident in sorted(hit_names):
                yield Finding(
                    rule=self.id,
                    path=pf.rel,
                    line=node.lineno,
                    symbol=pf.symbol_of(node),
                    key=ident,
                    message=(
                        f"secret {ident!r} reaches a log call — log a "
                        f"length/digest or drop it (taxonomy: "
                        f"analysis/taxonomy.py)"
                    ),
                )


class SecretInException(Rule):
    id = "MPL102"
    summary = "secret material must not be interpolated into exceptions"

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        extra = pf.extra_secrets
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Raise) or node.exc is None:
                continue
            exc = node.exc
            if not isinstance(exc, ast.Call):
                continue
            hit: Set[str] = set()
            for arg in list(exc.args) + [kw.value for kw in exc.keywords]:
                # only interpolation leaks: f-strings, str()/repr()/format
                # wrappers, % / + composition. A bare secret positional
                # arg also leaks via str(exc).
                for s in _secret_names_in(arg, extra):
                    hit.add(s.id if isinstance(s, ast.Name) else s.attr)
            for ident in sorted(hit):
                yield Finding(
                    rule=self.id,
                    path=pf.rel,
                    line=node.lineno,
                    symbol=pf.symbol_of(node),
                    key=ident,
                    message=(
                        f"secret {ident!r} interpolated into a raised "
                        f"exception — tracebacks end up in logs"
                    ),
                )


class SecretCompare(Rule):
    id = "MPL103"
    summary = "secret/MAC comparison must use hmac.compare_digest"

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        extra = pf.extra_secrets
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Compare):
                continue
            if not any(isinstance(op, (ast.Eq, ast.NotEq)) for op in node.ops):
                continue
            sides = [node.left] + list(node.comparators)
            # `x is None`-adjacent shapes and length checks don't count:
            # only flag when a *sensitive-named* operand is compared to
            # another non-constant expression
            sensitive = None
            other_nonconst = False
            for s in sides:
                ident = ""
                if isinstance(s, ast.Name):
                    ident = s.id
                elif isinstance(s, ast.Attribute):
                    ident = s.attr
                if ident and is_compare_sensitive(ident, extra):
                    sensitive = ident
                elif not isinstance(s, ast.Constant):
                    other_nonconst = True
            if sensitive and other_nonconst:
                yield Finding(
                    rule=self.id,
                    path=pf.rel,
                    line=node.lineno,
                    symbol=pf.symbol_of(node),
                    key=sensitive,
                    message=(
                        f"timing-unsafe == / != on {sensitive!r} — use "
                        f"hmac.compare_digest for secret/MAC bytes"
                    ),
                )
