"""MPL5xx — wire & thread hygiene.

MPL501  every dataclass message type in ``wire.py`` must carry a
        ``v`` version field and its ``from_json`` must read it — PR 5
        added SLO fields by luck of the default-tolerant parser; a
        version field makes evolution deliberate. (Byte-compat is
        enforced at runtime by the wire tests: ``v`` is omitted from the
        encoded form while 0, so legacy signed envelopes stay
        bit-identical.)
MPL502  every ``threading.Thread``/``Timer`` constructed in the package
        must be daemonized at the constructor (``daemon=True``), or
        daemonized on the named variable before start, or carry a name
        registered in ``utils.annotations.REGISTERED_THREAD_PREFIXES``
        (the conftest leak-checker exempts those). Anything else leaks
        past interpreter shutdown and trips the tier-1 leak fixture at
        the worst time.
"""
from __future__ import annotations

import ast
from typing import Iterator, List, Optional, Set

from ..core import Finding, LintContext, ParsedFile, Rule, dotted_name, self_attr

try:  # the registry lives in product code so runtime can use it too
    from mpcium_tpu.utils.annotations import REGISTERED_THREAD_PREFIXES
except Exception:  # pragma: no cover - analysis usable standalone
    REGISTERED_THREAD_PREFIXES = ("ot-host",)

_WIRE_FILE = "mpcium_tpu/wire.py"
_THREAD_CTORS = {"threading.Thread", "Thread", "threading.Timer", "Timer"}


def _is_dataclass(cls: ast.ClassDef) -> bool:
    for dec in cls.decorator_list:
        name = dotted_name(dec if not isinstance(dec, ast.Call) else dec.func)
        if name.rsplit(".", 1)[-1] == "dataclass":
            return True
    return False


class WireVersionRoundTrip(Rule):
    id = "MPL501"
    summary = "wire message types must carry and parse a version field"

    def applies(self, rel: str) -> bool:
        return rel == _WIRE_FILE

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        for cls in pf.tree.body:
            if not isinstance(cls, ast.ClassDef) or not _is_dataclass(cls):
                continue
            has_v = any(
                isinstance(stmt, ast.AnnAssign)
                and isinstance(stmt.target, ast.Name)
                and stmt.target.id == "v"
                for stmt in cls.body
            )
            if not has_v:
                yield Finding(
                    rule=self.id,
                    path=pf.rel,
                    line=cls.lineno,
                    symbol=cls.name,
                    key="missing-v",
                    message=(
                        f"wire dataclass {cls.name} has no 'v' version "
                        f"field — add `v: int = 0` (omit from encoding "
                        f"while 0 to stay byte-compatible)"
                    ),
                )
                continue
            from_json = next(
                (
                    m
                    for m in cls.body
                    if isinstance(m, (ast.FunctionDef, ast.AsyncFunctionDef))
                    and m.name == "from_json"
                ),
                None,
            )
            if from_json is not None:
                reads_v = any(
                    isinstance(n, ast.Constant) and n.value == "v"
                    for n in ast.walk(from_json)
                )
                if not reads_v:
                    yield Finding(
                        rule=self.id,
                        path=pf.rel,
                        line=from_json.lineno,
                        symbol=f"{cls.name}.from_json",
                        key="v-not-parsed",
                        message=(
                            f"{cls.name}.from_json never reads the 'v' "
                            f"field — decoded messages silently lose their "
                            f"version"
                        ),
                    )


class UnmanagedThread(Rule):
    id = "MPL502"
    summary = "threads must be daemonized or leak-checker-registered"

    def _registered_name(self, call: ast.Call) -> bool:
        for kw in call.keywords:
            if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                v = kw.value.value
                if isinstance(v, str) and v.startswith(
                    tuple(REGISTERED_THREAD_PREFIXES)
                ):
                    return True
        return False

    def check(self, pf: ParsedFile, ctx: LintContext) -> Iterator[Finding]:
        # names daemonized anywhere in the file: `t.daemon = True`,
        # `self._x.daemon = True`
        daemonized: Set[str] = set()
        for node in ast.walk(pf.tree):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Attribute)
                    and t.attr == "daemon"
                    and isinstance(node.value, ast.Constant)
                    and node.value.value is True
                ):
                    owner = t.value
                    if isinstance(owner, ast.Name):
                        daemonized.add(owner.id)
                    else:
                        sa = self_attr(owner)
                        if sa:
                            daemonized.add(sa)
        for node in ast.walk(pf.tree):
            ctor: Optional[ast.Call] = None
            bound: List[str] = []
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                ctor = node.value
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        bound.append(t.id)
                    else:
                        sa = self_attr(t)
                        if sa:
                            bound.append(sa)
            elif isinstance(node, ast.Expr) and isinstance(node.value, ast.Call):
                ctor = node.value
            if ctor is None or dotted_name(ctor.func) not in _THREAD_CTORS:
                continue
            daemon_kw = any(
                kw.arg == "daemon"
                and isinstance(kw.value, ast.Constant)
                and kw.value.value is True
                for kw in ctor.keywords
            )
            if daemon_kw or self._registered_name(ctor):
                continue
            if any(b in daemonized for b in bound):
                continue
            kind = dotted_name(ctor.func).rsplit(".", 1)[-1]
            yield Finding(
                rule=self.id,
                path=pf.rel,
                line=ctor.lineno,
                symbol=pf.symbol_of(node),
                key=f"{kind}:{bound[0] if bound else 'anonymous'}",
                message=(
                    f"{kind} created without daemon=True and not registered "
                    f"with the leak-checker (utils.annotations."
                    f"REGISTERED_THREAD_PREFIXES) — it will outlive shutdown"
                ),
            )
