"""``mpclint`` command-line interface.

Exit codes: 0 clean (every finding grandfathered, no stale entries),
1 violations (new findings, stale baseline entries, or parse errors),
2 operator error (bad baseline file, bad arguments).

Usage:
    python scripts/mpclint.py [paths...]          # sweep, gate on baseline
    python scripts/mpclint.py --no-baseline       # raw sweep, gate on zero
    python scripts/mpclint.py --write-baseline    # grandfather current state
    python scripts/mpclint.py --list-rules
"""
from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path
from typing import Optional, Sequence

from .baseline import DEFAULT_BASELINE, BaselineError, load_baseline, write_baseline
from .core import run_lint
from .rules import rule_catalog


def _repo_root() -> Path:
    return Path(__file__).resolve().parents[2]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mpclint",
        description="mpcium-tpu project-native static analysis",
    )
    p.add_argument(
        "paths",
        nargs="*",
        type=Path,
        help="files/dirs to lint (default: the mpcium_tpu package)",
    )
    p.add_argument(
        "--baseline",
        type=Path,
        default=None,
        help=f"baseline file (default: <repo>/{DEFAULT_BASELINE})",
    )
    p.add_argument(
        "--no-baseline",
        action="store_true",
        help="ignore the baseline: any finding fails",
    )
    p.add_argument(
        "--write-baseline",
        action="store_true",
        help="grandfather every current finding (edit justifications before commit)",
    )
    p.add_argument("--list-rules", action="store_true", help="print the rule catalog")
    p.add_argument(
        "-q", "--quiet", action="store_true", help="summary line only"
    )
    return p


def main(argv: Optional[Sequence[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    out = sys.stdout

    if args.list_rules:
        for rule in rule_catalog():
            out.write(f"{rule.id}  {rule.summary}\n")
        return 0

    root = _repo_root()
    baseline_path = args.baseline or (root / DEFAULT_BASELINE)
    t0 = time.monotonic()
    result = run_lint(paths=args.paths or None, root=root)
    elapsed = time.monotonic() - t0

    for err in result.parse_errors:
        out.write(f"PARSE ERROR: {err}\n")

    if args.write_baseline:
        b = write_baseline(baseline_path, result.findings, "")
        out.write(
            f"wrote {len(b.entries)} entries to {baseline_path} — edit each "
            f"justification before committing\n"
        )
        return 0

    if args.no_baseline:
        new, grandfathered, stale = list(result.findings), [], []
    else:
        try:
            baseline = load_baseline(baseline_path)
        except BaselineError as e:
            out.write(f"BASELINE ERROR: {e}\n")
            return 2
        # standalone mpclint runs only the MPL rules — MPF staleness is
        # scripts/check_all.py's business (it runs both analyzers)
        new, grandfathered, stale = baseline.split(
            result.findings, scope=("MPL",)
        )

    if not args.quiet:
        for f in new:
            out.write(f.render() + "\n")
        for fp in stale:
            out.write(
                f"STALE BASELINE ENTRY: {fp} — the finding no longer fires; "
                f"delete it from {baseline_path.name}\n"
            )
    out.write(
        f"mpclint: {result.files_scanned} files in {elapsed:.2f}s — "
        f"{len(new)} new, {len(grandfathered)} grandfathered, "
        f"{len(stale)} stale\n"
    )
    failed = bool(new or stale or result.parse_errors)
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
