"""Config system (the viper analogue, reference pkg/config).

`config.yaml` in the working directory (or an explicit path), with
environment-variable overrides: ``MPCIUM_<KEY>`` where ``.`` → ``_``
(reference init.go:48-61, e.g. ``MPCIUM_MPC_THRESHOLD=2``). Secrets are
masked in serialized dumps (init.go:21-33)."""
from __future__ import annotations

import os
import threading
from dataclasses import dataclass, fields
from pathlib import Path
from typing import Any, Dict, Optional

_SECRET_KEYS = {"badger_password", "passphrase", "broker_token"}


@dataclass
class AppConfig:
    mpc_threshold: int = 2
    environment: str = "development"
    event_initiator_pubkey: str = ""  # hex
    badger_password: str = ""
    identity_dir: str = "identity"
    db_dir: str = "./db"
    control_kv_dir: str = "./control"  # FileKV root (the Consul analogue)
    # "file": FileKV directory (single-host dev; needs a shared volume for
    # multi-process). "broker": KV served by the broker over the network —
    # nodes share ONLY broker addresses, the multi-host deployment model
    # (reference serves this via Consul HTTP(S), consul.go:19-47)
    control_plane: str = "file"
    safe_prime_pool: str = ""
    passphrase: str = ""  # identity decryption (or prompt)
    broker_host: str = "127.0.0.1"  # TCP bus (the NATS analogue)
    broker_port: int = 4333
    broker_token: str = ""  # shared auth token (reference NATS credentials)
    broker_encrypt: bool = False  # AEAD channel (reference prod TLS posture)
    broker_journal: str = ""  # queue journal path ("" = in-memory queues)
    broker_standbys: str = ""  # failover endpoints, "host:port[,host:port]"
    batch_signing: bool = False  # TPU batch scheduler for ed25519 signing
    batch_window_s: float = 0.05
    # SLO-aware continuous batching (consumers/batch_scheduler.py)
    batch_max_batch: int = 1024  # dispatch at this many entries OR window age
    batch_manifest_timeout_s: float = 2.0  # deputy takeover at T, fallback 2T
    batch_patience_s: float = 900.0  # decline-responder / covered-entry TTL
    batch_deadline_ms: int = 30000  # default per-request deadline budget
    batch_max_queue_depth: int = 100000  # intake bound; over-depth submits shed
    batch_decline_cap: int = 64  # concurrent decline responders (oldest evicted)
    chaos_fault_plan: str = ""  # path to a faults.FaultPlan JSON ("" = off)
    session_wal: bool = False  # encrypted per-round session WAL + crash resume
    peers_file: str = "peers.json"
    # warm-start pass (mpcium_tpu.warm): pre-compile the serving set at
    # boot between mark_warming() and mark_ready() — see PERFORMANCE.md
    # "Warm start"
    warm_enabled: bool = False
    warm_budget_s: float = 300.0  # boot stays "warming" at most this long
    warm_schemes: str = "eddsa"  # comma list of eddsa,ecdsa,dkg,reshare ("" = all)
    warm_max_b: int = 64  # largest batch bucket to pre-warm
    warm_cache_dir: str = ""  # "" = <db_dir>/<node>/warm_cache_<hostfp>

    def to_json(self, mask_secrets: bool = True) -> Dict[str, Any]:
        out = {}
        for f in fields(self):
            v = getattr(self, f.name)
            if mask_secrets and f.name in _SECRET_KEYS and v:
                v = "********"
            out[f.name] = v
        return out


_config: Optional[AppConfig] = None
_lock = threading.Lock()


def init_config(path: Optional[str] = None, **overrides) -> AppConfig:
    """Load config.yaml + env overrides + explicit overrides."""
    global _config
    import yaml

    data: Dict[str, Any] = {}
    cfg_path = Path(path) if path else Path("config.yaml")
    if cfg_path.exists():
        data.update(yaml.safe_load(cfg_path.read_text()) or {})
    def _coerce(current, raw):
        # bool("false") is True — parse the usual spellings explicitly
        if isinstance(current, bool) and isinstance(raw, str):
            return raw.strip().lower() in ("1", "true", "yes", "on")
        return type(current)(raw)

    cfg = AppConfig()
    for f in fields(AppConfig):
        if f.name in data:
            setattr(cfg, f.name, _coerce(getattr(cfg, f.name), data[f.name]))
        env = os.environ.get("MPCIUM_" + f.name.upper().replace(".", "_"))
        if env is not None:
            setattr(cfg, f.name, _coerce(getattr(cfg, f.name), env))
    for k, v in overrides.items():
        if v is not None:
            setattr(cfg, k, v)
    with _lock:
        _config = cfg
    return cfg


def get_config() -> AppConfig:
    global _config
    with _lock:
        if _config is None:
            _config = AppConfig()
        return _config


def check_required(cfg: AppConfig, keys) -> None:
    """Reference checkRequiredConfigValues (main.go:278-288)."""
    missing = [k for k in keys if not getattr(cfg, k, None)]
    if missing:
        raise SystemExit(
            f"missing required config values: {', '.join(missing)} "
            f"(set in config.yaml or MPCIUM_<KEY> env)"
        )
