"""SLO load soak: bursty mixed traffic against a LocalCluster under chaos.

The serving claim of the batching work (PERFORMANCE.md) is not "a batch
completed once" but "the cluster holds its latency SLO under sustained
bursty load while the network misbehaves, and every request it cannot
serve is refused LOUDLY". This module is that claim's harness:

- a seeded traffic generator drives sign-dominant bursts (plus optional
  keygen/resharing rotations) at a :class:`~mpcium_tpu.cluster.LocalCluster`
  running the SLO scheduler, with a fault plan (default: the
  ``batch-chaos`` catalog entry — delay jitter on every batched-session
  round + drops on the acked unicast channel) active on every node;
- each request carries a lane (interactive/bulk) and a deadline; shed
  requests (backpressure or deadline expiry — always ``retryable`` error
  events, never silence) are retried with fresh tx ids up to a budget,
  and latency is measured from the ORIGINAL submission;
- the report closes the books: ``submitted == succeeded + shed + failed``
  with ``pending == 0`` is the no-silent-drops invariant the smoke test
  and the committed SOAK_*.json runs assert.

Run via ``scripts/load_soak.py`` (or ``make soak``).
"""
from __future__ import annotations

import json
import queue
import threading
import time
from dataclasses import asdict, dataclass
from typing import Dict, List, Optional

from . import wire
from .cluster import LocalCluster, load_test_preparams
from .perf.envfp import env_fingerprint
from .utils import log


@dataclass
class SoakConfig:
    # cluster shape
    n_nodes: int = 3
    threshold: int = 1
    n_wallets: int = 8
    root_dir: Optional[str] = None
    # traffic mix (sign-dominant, like the production workload)
    n_sign: int = 96
    n_keygen: int = 0
    n_reshare: int = 0
    burst_size: int = 16
    burst_gap_s: float = 0.3
    seed: int = 1337
    # SLO shape
    interactive_fraction: float = 0.25
    interactive_deadline_ms: int = 120_000
    bulk_deadline_ms: int = 600_000
    max_retries: int = 2
    retry_backoff_s: float = 0.5
    # chaos (named_plan entry; "" disables fault injection)
    chaos: str = "batch-chaos"
    chaos_seed: int = 7
    chaos_scale: float = 1.0
    # scheduler knobs under test
    batch_window_s: float = 0.25
    batch_max_batch: int = 1024
    batch_max_queue_depth: int = 100_000
    manifest_timeout_s: float = 120.0
    # harness limits
    warmup_signs: int = 0  # pre-clock requests to absorb cold XLA compiles
    wait_timeout_s: float = 900.0


@dataclass
class _Req:
    kind: str  # "sign" | "keygen" | "reshare"
    base_id: str
    wallet_id: str
    lane: str
    deadline_ms: int
    tx: bytes = b""
    submitted_at: float = 0.0
    attempts: int = 0
    status: str = "pending"  # pending|succeeded|shed|failed
    done_at: float = 0.0
    warmup: bool = False


def _pct(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    i = max(0, min(len(sorted_vals) - 1,
                   int(round(q / 100.0 * len(sorted_vals) + 0.5)) - 1))
    return sorted_vals[i]


def _latency_summary(vals_ms: List[float]) -> dict:
    s = sorted(vals_ms)
    return {
        "count": len(s),
        "p50": round(_pct(s, 50), 1),
        "p90": round(_pct(s, 90), 1),
        "p99": round(_pct(s, 99), 1),
        "max": round(s[-1], 1) if s else 0.0,
        "mean": round(sum(s) / len(s), 1) if s else 0.0,
    }


class SoakRun:
    """One soak execution: owns the cluster, the result subscriptions,
    the retry worker, and the request ledger keyed by base id."""

    def __init__(self, cfg: SoakConfig):
        self.cfg = cfg
        # deterministic traffic: the schedule (wallet choice, lanes, tx
        # bytes) derives entirely from cfg.seed
        import random

        self._rng = random.Random(cfg.seed)
        self._lock = threading.Lock()
        self._reqs: Dict[str, _Req] = {}
        self._all_done = threading.Event()
        self._retry_q: "queue.Queue[str]" = queue.Queue()
        self._stop = threading.Event()
        self._retries = 0
        self._late_events = 0

        fault_plans = None
        self._plan = None
        if cfg.chaos:
            from .faults.plan import named_plan

            self._plan = named_plan(
                cfg.chaos, seed=cfg.chaos_seed, scale=cfg.chaos_scale
            )
            fault_plans = {"*": self._plan}

        self.cluster = LocalCluster(
            n_nodes=cfg.n_nodes,
            threshold=cfg.threshold,
            root_dir=cfg.root_dir,
            preparams=load_test_preparams(),
            batch_signing=True,
            batch_window_s=cfg.batch_window_s,
            reply_timeout_s=60.0,
            fault_plans=fault_plans,
            batch_max_batch=cfg.batch_max_batch,
            batch_max_queue_depth=cfg.batch_max_queue_depth,
        )
        for ec in self.cluster.consumers:
            ec.scheduler.manifest_timeout_s = cfg.manifest_timeout_s

        # dealer-dealt ed25519 wallets: the soak measures SERVING, not DKG
        # (DKG has its own batched path, exercised by n_keygen > 0)
        from .engine import eddsa_batch as eb

        ids = self.cluster.node_ids
        shares = eb.dealer_keygen_batch(
            cfg.n_wallets, ids, threshold=cfg.threshold
        )
        self.wallets = [f"soakw{w}" for w in range(cfg.n_wallets)]
        for w, wid in enumerate(self.wallets):
            for i, nid in enumerate(ids):
                self.cluster.nodes[nid].save_share(shares[i][w], wid)

        self._subs = [
            self.cluster.client.on_sign_result(self._on_sign),
            self.cluster.client.on_wallet_creation_result(self._on_keygen),
            self.cluster.client.on_resharing_result(self._on_reshare),
        ]
        self._retrier = threading.Thread(
            target=self._retry_loop, name="soak-retrier", daemon=True
        )
        self._retrier.start()

    # -- result classification ---------------------------------------------

    def _terminal(self, base_id: str, ev_kind: str, ok: bool,
                  retryable: bool) -> None:
        """Apply one result event to the ledger. First terminal outcome
        wins; duplicates (chaos) and post-terminal stragglers are counted
        but ignored. A retryable failure consumes an attempt and either
        requeues or goes terminal-shed."""
        retry = False
        with self._lock:
            r = self._reqs.get(base_id)
            if r is None or r.kind != ev_kind or r.status != "pending":
                self._late_events += 1
                return
            if ok:
                r.status = "succeeded"
                r.done_at = time.monotonic()
            elif retryable and r.attempts <= self.cfg.max_retries:
                retry = True  # requeue outside the lock
            else:
                r.status = "shed" if retryable else "failed"
                r.done_at = time.monotonic()
            self._check_done_locked()
        if retry:
            self._retry_q.put(base_id)

    def _on_sign(self, ev: wire.SigningResultEvent) -> None:
        base = ev.tx_id.split("~r")[0]
        self._terminal(base, "sign",
                       ev.result_type == wire.RESULT_SUCCESS,
                       bool(getattr(ev, "retryable", False)))

    def _on_keygen(self, ev: wire.KeygenSuccessEvent) -> None:
        self._terminal(ev.wallet_id, "keygen",
                       ev.result_type == wire.RESULT_SUCCESS,
                       bool(getattr(ev, "retryable", False)))

    def _on_reshare(self, ev: wire.ResharingSuccessEvent) -> None:
        self._terminal(ev.wallet_id, "reshare",
                       ev.result_type == wire.RESULT_SUCCESS,
                       bool(getattr(ev, "retryable", False)))

    def _check_done_locked(self) -> None:
        if all(r.status != "pending" for r in self._reqs.values()):
            self._all_done.set()

    # -- submission ---------------------------------------------------------

    def _submit(self, r: _Req) -> None:
        """(Re)issue a request. Sign retries use a fresh tx id — the
        durable queue dedups on tx id for its window, and the scheduler's
        claim for the shed attempt was released, so a fresh id is both
        necessary and sufficient."""
        r.attempts += 1
        if r.submitted_at == 0.0:
            r.submitted_at = time.monotonic()
        if r.kind == "sign":
            tx_id = (r.base_id if r.attempts == 1
                     else f"{r.base_id}~r{r.attempts - 1}")
            self.cluster.client.sign_transaction(wire.SignTxMessage(
                key_type="ed25519",
                wallet_id=r.wallet_id,
                network_internal_code="sol",
                tx_id=tx_id,
                tx=r.tx,
                deadline_ms=r.deadline_ms,
                priority=r.lane,
            ))
        elif r.kind == "keygen":
            # GenerateKeyMessage carries no SLO fields (frozen wire
            # format) — keygen rides the config-default deadline
            self.cluster.client.create_wallet(r.wallet_id)
        else:
            self.cluster.client.resharing(
                r.wallet_id, self.cfg.threshold, "ed25519",
                deadline_ms=r.deadline_ms, priority=r.lane,
            )

    def _retry_loop(self) -> None:
        while not self._stop.is_set():
            try:
                base_id = self._retry_q.get(timeout=0.2)
            except queue.Empty:
                continue
            self._stop.wait(self.cfg.retry_backoff_s)
            with self._lock:
                r = self._reqs.get(base_id)
                if r is None or r.status != "pending":
                    continue
                self._retries += 1
            try:
                self._submit(r)
            except Exception as e:  # noqa: BLE001 — soak must keep counting
                with self._lock:
                    r.status = "failed"
                    r.done_at = time.monotonic()
                    self._check_done_locked()
                log.warn("soak retry submit failed",
                         req=base_id, error=repr(e))

    def _mk_sign(self, i: int, warmup: bool = False) -> _Req:
        rng = self._rng
        lane = (wire.PRIORITY_INTERACTIVE
                if rng.random() < self.cfg.interactive_fraction
                else wire.PRIORITY_BULK)
        return _Req(
            kind="sign",
            base_id=f"{'warm' if warmup else 'soak'}-s{i}",
            wallet_id=self.wallets[rng.randrange(len(self.wallets))],
            lane=lane,
            deadline_ms=(self.cfg.interactive_deadline_ms
                         if lane == wire.PRIORITY_INTERACTIVE
                         else self.cfg.bulk_deadline_ms),
            tx=bytes(rng.getrandbits(8) for _ in range(32)),
            warmup=warmup,
        )

    # -- the run ------------------------------------------------------------

    def run(self) -> dict:
        try:
            return self._run_inner()
        finally:
            self._stop.set()
            self._retrier.join(5.0)
            for sub in self._subs:
                try:
                    sub.unsubscribe()
                except Exception:  # noqa: BLE001
                    pass
            self.cluster.close()

    def _run_inner(self) -> dict:
        cfg = self.cfg
        # warmup: absorb cold XLA compiles (minutes on a fresh cache)
        # before the measured clock starts; warmup requests are ledgered
        # (accounting stays closed) but excluded from the report totals
        if cfg.warmup_signs > 0:
            warm = [self._mk_sign(i, warmup=True)
                    for i in range(cfg.warmup_signs)]
            with self._lock:
                for r in warm:
                    self._reqs[r.base_id] = r
            for r in warm:
                self._submit(r)
            self._wait_all(cfg.wait_timeout_s, what="warmup")
            with self._lock:
                self._all_done.clear()
            log.info("soak warmup complete", signs=cfg.warmup_signs)

        # the measured schedule: interleave keygen/reshare requests into
        # the sign burst sequence deterministically
        reqs: List[_Req] = [self._mk_sign(i) for i in range(cfg.n_sign)]
        for k in range(cfg.n_keygen):
            reqs.append(_Req(kind="keygen", base_id=f"soak-kg{k}",
                             wallet_id=f"soak-kg{k}",
                             lane=wire.PRIORITY_BULK,
                             deadline_ms=cfg.bulk_deadline_ms))
        for k in range(cfg.n_reshare):
            wid = self.wallets[self._rng.randrange(len(self.wallets))]
            reqs.append(_Req(kind="reshare", base_id=wid, wallet_id=wid,
                             lane=wire.PRIORITY_BULK,
                             deadline_ms=cfg.bulk_deadline_ms))
        # dedupe reshare targets (one rotation per wallet per soak) and
        # spread the non-sign requests through the burst train
        seen, uniq = set(), []
        for r in reqs:
            if r.base_id in seen:
                continue
            seen.add(r.base_id)
            uniq.append(r)
        reqs = uniq
        self._rng.shuffle(reqs)
        with self._lock:
            for r in reqs:
                self._reqs[r.base_id] = r

        t0 = time.monotonic()
        for i in range(0, len(reqs), cfg.burst_size):
            for r in reqs[i:i + cfg.burst_size]:
                self._submit(r)
            if i + cfg.burst_size < len(reqs):
                time.sleep(cfg.burst_gap_s)
        self._wait_all(cfg.wait_timeout_s, what="soak traffic")
        t1 = time.monotonic()
        return self._report(reqs, t0, t1)

    def _wait_all(self, timeout_s: float, what: str) -> None:
        with self._lock:
            self._check_done_locked()
        if not self._all_done.wait(timeout_s):
            with self._lock:
                pending = [b for b, r in self._reqs.items()
                           if r.status == "pending"]
            log.warn(f"{what}: requests still pending at timeout",
                     pending=len(pending), sample=pending[:8])

    # -- reporting ----------------------------------------------------------

    def _report(self, reqs: List[_Req], t0: float, t1: float) -> dict:
        cfg = self.cfg
        with self._lock:
            measured = [r for r in self._reqs.values() if not r.warmup]
            by_status: Dict[str, int] = {}
            for r in measured:
                by_status[r.status] = by_status.get(r.status, 0) + 1
            lat_ms = {
                "overall": [], wire.PRIORITY_INTERACTIVE: [],
                wire.PRIORITY_BULK: [],
            }
            under_slo = 0
            signed = 0
            for r in measured:
                if r.status != "succeeded":
                    continue
                ms = (r.done_at - r.submitted_at) * 1000.0
                lat_ms["overall"].append(ms)
                lat_ms[r.lane].append(ms)
                if r.kind == "sign":
                    signed += 1
                    if ms <= r.deadline_ms:
                        under_slo += 1
            retries = self._retries
            late = self._late_events

        duration_s = max(t1 - t0, 1e-9)
        snap = self.cluster.metrics_snapshot()

        def _ctr(name: str) -> float:
            return sum(s["counters"].get(name, 0.0) for s in snap.values())

        submitted = len(measured)
        succeeded = by_status.get("succeeded", 0)
        shed = by_status.get("shed", 0)
        failed = by_status.get("failed", 0)
        pending = by_status.get("pending", 0)
        report = {
            "config": asdict(cfg),
            "chaos": {
                "plan": cfg.chaos or None,
                "seed": cfg.chaos_seed,
                "scale": cfg.chaos_scale,
                "rules": self._plan.describe() if self._plan else [],
            },
            "outcomes": {
                "submitted": submitted,
                "succeeded": succeeded,
                "shed": shed,
                "failed": failed,
                "pending": pending,
                "retries": retries,
                "late_or_duplicate_events": late,
            },
            "by_kind": {
                k: {
                    "submitted": sum(1 for r in measured if r.kind == k),
                    "succeeded": sum(1 for r in measured
                                     if r.kind == k
                                     and r.status == "succeeded"),
                }
                for k in ("sign", "keygen", "reshare")
            },
            "latency_ms": {k: _latency_summary(v)
                           for k, v in lat_ms.items()},
            "throughput": {
                "duration_s": round(duration_s, 2),
                "sigs_per_s": round(signed / duration_s, 3),
                "sigs_per_s_under_slo": round(under_slo / duration_s, 3),
                "slo_hit_rate": round(under_slo / signed, 4) if signed else 0.0,
            },
            "scheduler": {
                "batches_fired": _ctr("scheduler.batches_fired_total"),
                "shed_total": _ctr("scheduler.shed_total"),
                "shed_backpressure": _ctr(
                    "scheduler.shed_backpressure_total"),
                "shed_deadline": _ctr("scheduler.shed_deadline_total"),
                "deputy_takeovers": _ctr("scheduler.deputy_takeover_total"),
                "fallbacks": _ctr("scheduler.fallback_total"),
                "per_node": snap,
            },
            # the no-silent-drops invariant: every submitted request
            # reached EXACTLY ONE terminal outcome
            "accounting_ok": (pending == 0
                              and submitted == succeeded + shed + failed),
            # env fingerprint (perf/envfp): which git sha / jax / host /
            # knob set produced this number — the grouping key the perf
            # ledger segregates trend lines by
            "env": env_fingerprint(),
            # cluster-wide Prometheus text exposition (also written as a
            # .prom sidecar by scripts/load_soak.py) and the merged
            # cross-node flight-recorder trace (Perfetto-loadable)
            "prometheus": self.cluster.prometheus_text(),
            "trace": self.cluster.trace_snapshot(
                clear=True,
                meta={"soak_seed": cfg.seed, "chaos": cfg.chaos or None},
            ),
        }
        return report


def run_soak(cfg: Optional[SoakConfig] = None) -> dict:
    """Run one soak and return its JSON-serializable report."""
    return SoakRun(cfg or SoakConfig()).run()


def write_report(report: dict, path: str) -> None:
    with open(path, "w") as f:
        json.dump(report, f, indent=2, sort_keys=False)
        f.write("\n")
