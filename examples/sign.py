"""Create a wallet, then sign one EdDSA and one ECDSA transaction through
the durable signing pipeline (the analogue of reference examples/sign).

Default: an in-process 3-node cluster; ``--config config.yaml`` connects
to a running broker+daemons deployment instead.

Usage: python examples/sign.py [--config config.yaml]
"""
import hashlib
import sys
import uuid

from mpcium_tpu import wire
from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.utils import log


def main() -> int:
    log.init()
    from _connect import connect

    cluster, args = connect(sys.argv[1:])
    try:
        wallet_id = f"wallet-{uuid.uuid4().hex[:8]}"
        ev = cluster.create_wallet_sync(wallet_id)
        print(f"wallet {wallet_id} created")

        # EdDSA (Solana-style)
        tx = b"transfer 1 SOL to Ghk9..."
        res = cluster.sign_sync(
            wire.SignTxMessage(
                key_type="ed25519",
                wallet_id=wallet_id,
                network_internal_code="solana-devnet",
                tx_id=f"tx-{uuid.uuid4().hex[:8]}",
                tx=tx,
            )
        )
        assert res.result_type == wire.RESULT_SUCCESS, res.error_reason
        ok = hm.ed25519_verify(
            bytes.fromhex(ev.eddsa_pub_key), tx, bytes.fromhex(res.signature)
        )
        print(f"eddsa signature: {res.signature[:32]}…  verified={ok}")

        # ECDSA (EVM-style, signs a 32-byte digest)
        digest = hashlib.sha256(b"eth transfer").digest()
        res = cluster.sign_sync(
            wire.SignTxMessage(
                key_type="secp256k1",
                wallet_id=wallet_id,
                network_internal_code="ethereum",
                tx_id=f"tx-{uuid.uuid4().hex[:8]}",
                tx=digest,
            )
        )
        assert res.result_type == wire.RESULT_SUCCESS, res.error_reason
        ok = hm.ecdsa_verify(
            hm.secp_decompress(bytes.fromhex(ev.ecdsa_pub_key)),
            int.from_bytes(digest, "big"),
            int(res.r, 16),
            int(res.s, 16),
        )
        print(f"ecdsa signature: r={res.r[:16]}… s={res.s[:16]}… "
              f"recovery={res.signature_recovery}  verified={ok}")
        return 0
    finally:
        cluster.close()


if __name__ == "__main__":
    sys.exit(main())
