"""Shared example bootstrap: in-process dev cluster by default, or a
live networked deployment with ``--config path/to/config.yaml`` (the
reference examples' mode — they assume a running NATS+Consul+nodes
stack, INSTALLATION.md "Start Mpcium Nodes")."""
from __future__ import annotations

import sys
from typing import List, Tuple


def connect(argv: List[str]) -> Tuple[object, List[str]]:
    """Returns (cluster, leftover_args). The cluster exposes
    create_wallet_sync / sign_sync / reshare_sync / close regardless of
    mode (mpcium_tpu.cluster.SyncOps)."""
    args = list(argv)
    if "--config" in args:
        i = args.index("--config")
        try:
            cfg = args[i + 1]
        except IndexError:
            print("--config requires a path", file=sys.stderr)
            raise SystemExit(2)
        del args[i : i + 2]
        from mpcium_tpu.cluster import RemoteCluster

        return RemoteCluster(cfg), args
    from mpcium_tpu.cluster import LocalCluster, load_test_preparams

    return (
        LocalCluster(n_nodes=3, threshold=1, preparams=load_test_preparams()),
        args,
    )
