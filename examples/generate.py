"""Create a wallet through the client SDK.

Default: an in-process 3-node cluster. With ``--config config.yaml`` the
client connects to a RUNNING broker+daemons deployment instead (the
reference examples/generate/main.go mode against a live stack).

Usage: python examples/generate.py [--config config.yaml] [wallet-id]
"""
import sys
import uuid

from mpcium_tpu.utils import log


def main() -> int:
    log.init()
    from _connect import connect

    cluster, args = connect(sys.argv[1:])
    wallet_id = args[0] if args else f"wallet-{uuid.uuid4().hex[:8]}"
    try:
        ev = cluster.create_wallet_sync(wallet_id)
        print(f"wallet created: {ev.wallet_id}")
        print(f"  ecdsa (secp256k1) pubkey: {ev.ecdsa_pub_key}")
        print(f"  eddsa (ed25519)  pubkey: {ev.eddsa_pub_key}")
        return 0
    finally:
        cluster.close()


if __name__ == "__main__":
    sys.exit(main())
