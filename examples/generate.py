"""Create a wallet through the client SDK against an in-process 3-node
cluster (the analogue of reference examples/generate/main.go run against a
docker-compose stack).

Usage: python examples/generate.py [wallet-id]
"""
import sys
import uuid

from mpcium_tpu.cluster import LocalCluster, load_test_preparams
from mpcium_tpu.utils import log


def main() -> int:
    wallet_id = sys.argv[1] if len(sys.argv) > 1 else f"wallet-{uuid.uuid4().hex[:8]}"
    log.init()
    cluster = LocalCluster(n_nodes=3, threshold=1, preparams=load_test_preparams())
    try:
        ev = cluster.create_wallet_sync(wallet_id)
        print(f"wallet created: {ev.wallet_id}")
        print(f"  ecdsa (secp256k1) pubkey: {ev.ecdsa_pub_key}")
        print(f"  eddsa (ed25519)  pubkey: {ev.eddsa_pub_key}")
        return 0
    finally:
        cluster.close()


if __name__ == "__main__":
    sys.exit(main())
