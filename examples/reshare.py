"""Create a wallet, rotate its committee, then sign with the reshared
shares (the analogue of reference examples/reshare — which, per SURVEY.md
§7.5, does not even compile upstream; this one runs).

Default: an in-process 3-node cluster; ``--config config.yaml`` connects
to a running broker+daemons deployment instead.

Usage: python examples/reshare.py [--config config.yaml]
"""
import sys
import uuid

from mpcium_tpu import wire
from mpcium_tpu.core import hostmath as hm
from mpcium_tpu.utils import log


def main() -> int:
    log.init()
    from _connect import connect

    cluster, args = connect(sys.argv[1:])
    try:
        wallet_id = f"wallet-{uuid.uuid4().hex[:8]}"
        ev = cluster.create_wallet_sync(wallet_id)
        print(f"wallet {wallet_id} created, eddsa pub {ev.eddsa_pub_key[:16]}…")

        res = cluster.reshare_sync(wallet_id, new_threshold=1, key_type="ed25519")
        print(f"reshared: pubkey unchanged = {res.pub_key == ev.eddsa_pub_key}")

        tx = b"post-rotation transfer"
        sres = cluster.sign_sync(
            wire.SignTxMessage(
                key_type="ed25519",
                wallet_id=wallet_id,
                network_internal_code="solana-devnet",
                tx_id=f"tx-{uuid.uuid4().hex[:8]}",
                tx=tx,
            )
        )
        assert sres.result_type == wire.RESULT_SUCCESS, sres.error_reason
        ok = hm.ed25519_verify(
            bytes.fromhex(ev.eddsa_pub_key), tx, bytes.fromhex(sres.signature)
        )
        print(f"post-rotation signature verified={ok}")
        return 0
    finally:
        cluster.close()


if __name__ == "__main__":
    sys.exit(main())
